"""Tier-1 paged-KV decode tests (serve/decode.py paged layout +
models/causal_lm.py + ops/pallas/paged_attention.py).

The paged cache's contracts, in dependency order: (1) paged FLOAT
prefill/decode is BITWISE the dense twin at every position — at full
page-table width only (truncating the key axis re-tiles the XLA
reduction, which is why float grids compile just the full-width decode
cell); (2) the engine's page allocator never leaks or double-books
across admit/evict churn, defers admissions an undersized pool cannot
back, and reuses reclaimed pages; (3) int8 KV token streams agree
>= 0.99 with the dense float baseline (the quantization accuracy gate);
(4) TP-sharded paged state (heads over the model axis) is bitwise the
unsharded run; (5) the Pallas kernel under ``interpret=True`` (the
off-TPU parity surface) matches the XLA gather reference at every
decode-grid page bucket, and its visits probe proves `pl.when` page
skipping; (6) memory-budget accounting charges pages actually pinned,
not the dense worst case. All CPU-mesh.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dist_mnist_tpu.cluster.mesh import activate
from dist_mnist_tpu.models.causal_lm import CausalLMTiny
from dist_mnist_tpu.obs import events
from dist_mnist_tpu.ops.pallas.paged_attention import (
    paged_attention,
    paged_attention_cost,
    paged_attention_pages,
    paged_attention_probe,
)
from dist_mnist_tpu.ops.quant import QuantizedArray, quantize_kv
from dist_mnist_tpu.serve import (
    CompiledModelCache,
    DecodeScheduler,
    build_decode_engine,
    init_lm_for_serving,
    run_decode_loadgen,
)
from dist_mnist_tpu.serve.decode import DecodeEngine
from dist_mnist_tpu.serve.zoo import DecodeGrid, default_decode_grid

# same small geometry as test_serve_decode.py; pages of 8 tokens give a
# 4-page-per-slot table — enough structure for every bucket shape
LM_KW = dict(vocab_size=64, dim=32, depth=2, heads=4, max_seq=32)
PAGE_T = 8
PPS = LM_KW["max_seq"] // PAGE_T
MAX_SLOTS = 4
PAGED_KW = dict(LM_KW, cache_layout="paged", kv_page_tokens=PAGE_T)
INT8_KW = dict(PAGED_KW, kv_quant="int8")


@pytest.fixture(scope="module")
def lm():
    model = CausalLMTiny(**LM_KW)
    params, _ = model.init(jax.random.PRNGKey(0))
    return model, params


@pytest.fixture(scope="module")
def dense_engine(mesh8):
    eng = build_decode_engine(mesh8, max_slots=MAX_SLOTS,
                              cache=CompiledModelCache(), **LM_KW)
    eng.prewarm()
    return eng


@pytest.fixture(scope="module")
def paged_engine(mesh8):
    eng = build_decode_engine(mesh8, max_slots=MAX_SLOTS,
                              cache=CompiledModelCache(), **PAGED_KW)
    eng.prewarm()
    return eng


@pytest.fixture(scope="module")
def int8_engine(mesh8):
    eng = build_decode_engine(mesh8, max_slots=MAX_SLOTS,
                              cache=CompiledModelCache(), **INT8_KW)
    eng.prewarm()
    return eng


def _identity_table(rows: int, pps: int = PPS) -> np.ndarray:
    """Row r owns pages [r*pps, (r+1)*pps) of an init_cache(rows) pool."""
    return np.arange(rows * pps, dtype=np.int32).reshape(rows, pps)


def _run_streams(engine, *, runahead=1, n=16, seed=7):
    with DecodeScheduler(engine, mode="continuous",
                         runahead=runahead) as sched:
        res = run_decode_loadgen(sched, n_requests=n, concurrency=8,
                                 seed=seed, keep_streams=True)
    assert res["recompiles_during_traffic"] == 0
    return res["streams"]


# -- grid: page buckets ------------------------------------------------------

def test_default_grid_page_buckets():
    flt = default_decode_grid(CausalLMTiny(**PAGED_KW),
                              max_slots=MAX_SLOTS)
    # float paged: ONLY the full-width cell (bitwise contract)
    assert flt.decode_page_buckets == (PPS,)
    i8 = default_decode_grid(CausalLMTiny(**INT8_KW), max_slots=MAX_SLOTS)
    assert i8.decode_page_buckets == (1, 2, PPS)
    assert [c for c in i8.cells() if c[0] == "decode"] == \
        [("decode", 1), ("decode", 2), ("decode", PPS)]
    assert i8.decode_page_bucket_for(1) == 1
    assert i8.decode_page_bucket_for(3) == PPS
    with pytest.raises(ValueError):
        i8.decode_page_bucket_for(PPS + 1)
    dense = default_decode_grid(CausalLMTiny(**LM_KW), max_slots=MAX_SLOTS)
    assert dense.decode_page_buckets == ()
    assert dense.cells()[-1] == ("decode",)
    with pytest.raises(ValueError):
        dense.decode_page_bucket_for(1)


# -- model: paged float is bitwise dense at every position -------------------

def test_paged_float_bitwise_dense_every_position(lm):
    model, params = lm
    paged = CausalLMTiny(**PAGED_KW)
    rng = np.random.default_rng(1)
    b, plen, steps = 2, 9, 12
    prompt = rng.integers(0, model.vocab_size, size=(b, plen),
                          dtype=np.int32)
    slots = np.arange(b, dtype=np.int32)
    lengths = np.full(b, plen, np.int32)
    table = _identity_table(b)

    d_cache = model.init_cache(b)
    d_last, d_cache = model.prefill(params, d_cache, prompt, slots,
                                    lengths)
    p_cache = paged.init_cache(b)
    p_last, p_cache = paged.prefill(params, p_cache, prompt, slots,
                                    lengths, page_table=table)
    np.testing.assert_array_equal(np.asarray(p_last), np.asarray(d_last))

    tok = np.argmax(np.asarray(d_last), axis=-1).astype(np.int32)
    pos = np.full(b, plen, np.int32)
    for _ in range(steps):
        d_log, d_cache = model.decode_step(params, d_cache, tok, pos)
        p_log, p_cache = paged.decode_step(params, p_cache, tok, pos,
                                           page_table=table)
        np.testing.assert_array_equal(np.asarray(p_log),
                                      np.asarray(d_log))
        tok = np.argmax(np.asarray(d_log), axis=-1).astype(np.int32)
        pos = pos + 1


# -- engine: allocator invariants --------------------------------------------

def _pinned(eng):
    return [p for pages in eng._slot_pages.values() for p in pages]


def test_page_allocator_churn_no_leak(paged_engine):
    eng = paged_engine
    allocatable = eng.num_pages - PPS
    scratch = set(int(p) for p in eng._scratch_pages)
    rng = np.random.default_rng(2)
    held: dict = {}
    for _ in range(200):
        slot = int(rng.integers(0, MAX_SLOTS))
        if slot in held:
            eng.release_slot(slot)
            del held[slot]
        else:
            total = int(rng.integers(1, LM_KW["max_seq"] + 1))
            if eng.try_reserve(slot, total):
                held[slot] = -(-total // PAGE_T)
        pinned = _pinned(eng)
        # disjoint, never scratch, conservation
        assert len(pinned) == len(set(pinned))
        assert not scratch & set(pinned)
        assert len(eng._free_pages) + len(pinned) == allocatable
        assert set(eng._free_pages).isdisjoint(pinned)
    for slot in list(held):
        eng.release_slot(slot)
    assert eng.kv_stats()["kv_pages_pinned"] == 0
    assert sorted(eng._free_pages) == list(range(allocatable))
    # released rows re-alias the scratch stripe; a fresh reserve reuses
    # reclaimed pages rather than growing the pool
    np.testing.assert_array_equal(eng._page_table[:MAX_SLOTS],
                                  np.tile(eng._scratch_pages,
                                          (MAX_SLOTS, 1)))
    assert eng.try_reserve(0, LM_KW["max_seq"])
    assert max(_pinned(eng)) < allocatable
    eng.release_slot(0)
    eng.release_slot(0)  # idempotent
    assert eng.kv_stats()["kv_pages_pinned"] == 0


def test_undersized_pool_defers_then_completes(mesh8, dense_engine):
    """A pool backing one slot at a time still finishes every request
    (admissions defer head-of-line until evictions reclaim pages) and
    the streams stay bitwise the dense baseline."""
    model, params = init_lm_for_serving("causal_tiny", seed=0, **PAGED_KW)
    grid = default_decode_grid(model, max_slots=MAX_SLOTS)
    eng = DecodeEngine(model, params, mesh8, model_name="causal_tiny",
                       grid=grid, num_pages=2 * PPS)
    eng.prewarm()
    assert eng.try_reserve(0, LM_KW["max_seq"])      # all 4 free pages
    assert not eng.try_reserve(1, PAGE_T)            # nothing left
    eng.release_slot(0)
    assert eng.try_reserve(1, PAGE_T)
    eng.release_slot(1)
    assert _run_streams(eng, seed=7) == _run_streams(dense_engine,
                                                     seed=7)
    assert eng.kv_stats()["kv_pages_pinned"] == 0


# -- engine/scheduler: stream parity -----------------------------------------

def test_paged_streams_bitwise_dense(dense_engine, paged_engine):
    assert _run_streams(paged_engine, seed=5) == \
        _run_streams(dense_engine, seed=5)


def test_runahead_overlap_streams_identical(paged_engine):
    """Host/device overlap moves WHEN admissions happen, never what any
    slot computes: runahead=1 and the serial loop produce identical
    streams."""
    assert _run_streams(paged_engine, runahead=1, seed=9) == \
        _run_streams(paged_engine, runahead=0, seed=9)


def test_int8_stream_agreement_gate(dense_engine, int8_engine):
    dense = _run_streams(dense_engine, n=24, seed=11)
    i8 = _run_streams(int8_engine, n=24, seed=11)
    assert len(dense) == len(i8)
    match = total = 0
    for a, b in zip(dense, i8):
        assert len(a) == len(b)  # greedy lengths are request-determined
        match += sum(x == y for x, y in zip(a, b))
        total += len(a)
    assert total > 0
    assert match / total >= 0.99


# -- TP: sharded paged cache bitwise unsharded -------------------------------

def test_tp_paged_bitwise_vs_unsharded(lm, mesh_tp):
    _, params = lm
    paged = CausalLMTiny(**PAGED_KW)
    rng = np.random.default_rng(4)
    b, plen = 2, 7
    prompt = rng.integers(0, paged.vocab_size, size=(b, plen),
                          dtype=np.int32)
    slots = np.arange(b, dtype=np.int32)
    lengths = np.full(b, plen, np.int32)
    table = _identity_table(b)

    def run():
        cache = paged.init_cache(b)
        last, cache = paged.prefill(params, cache, prompt, slots,
                                    lengths, page_table=table)
        tok = jnp.argmax(last, axis=-1).astype(jnp.int32)
        step, cache = paged.decode_step(params, cache, tok,
                                        np.full(b, plen, np.int32),
                                        page_table=table)
        return np.asarray(last), np.asarray(step), np.asarray(cache["k"])

    ref_last, ref_step, ref_k = run()
    with activate(mesh_tp):
        tp_last, tp_step, tp_k = run()
    np.testing.assert_array_equal(tp_last, ref_last)
    np.testing.assert_array_equal(tp_step, ref_step)
    np.testing.assert_array_equal(tp_k, ref_k)


# -- kernel: parity + page skipping ------------------------------------------

def _quant_pool(rng, n_pages, t=PAGE_T, h=2, d=16):
    x = jnp.asarray(rng.standard_normal((n_pages, t, h, d)), jnp.float32)
    q, s = quantize_kv(x)
    return QuantizedArray(q, s, "kv_head")


def _gather_ref(q, kp, vp, table, lengths):
    """The XLA-path semantics in plain numpy: gather pages through the
    table, dequantize, masked softmax attention per (row, head)."""
    k = np.asarray(kp.q, np.float32) * np.asarray(kp.scale, np.float32)
    v = np.asarray(vp.q, np.float32) * np.asarray(vp.scale, np.float32)
    r, _, h, d = q.shape
    n, t = table.shape[1], k.shape[1]
    out = np.zeros((r, h, d), np.float32)
    for i in range(r):
        ki = k[table[i]].reshape(n * t, h, d)
        vi = v[table[i]].reshape(n * t, h, d)
        ln = int(lengths[i])
        for j in range(h):
            logits = ki[:ln, j] @ np.asarray(q[i, 0, j]) / np.sqrt(d)
            p = np.exp(logits - logits.max())
            out[i, j] = (p / p.sum()) @ vi[:ln, j]
    return out


@pytest.mark.parametrize("n_pages", [1, 2, PPS])
def test_kernel_parity_every_page_bucket(n_pages):
    """interpret=True parity at every decode-grid page bucket, random
    tables and ragged lengths — the same cells the int8 engine runs."""
    rng = np.random.default_rng(20 + n_pages)
    rows, pool = MAX_SLOTS + 1, 12
    kp = _quant_pool(rng, pool)
    vp = _quant_pool(rng, pool)
    q = jnp.asarray(rng.standard_normal((rows, 1, 2, 16)), jnp.float32)
    table = np.stack([rng.choice(pool, size=n_pages, replace=False)
                      for _ in range(rows)]).astype(np.int32)
    lengths = rng.integers(1, n_pages * PAGE_T + 1,
                           size=rows).astype(np.int32)
    out = paged_attention(q, kp, vp, jnp.asarray(table),
                          jnp.asarray(lengths), interpret=True)
    ref = _gather_ref(np.asarray(q), kp, vp, table, lengths)
    np.testing.assert_allclose(np.asarray(out)[:, 0], ref,
                               rtol=2e-5, atol=2e-6)


def test_kernel_visits_probe_counts_active_pages():
    """`pl.when` page skipping is structural: the visits probe equals
    ceil(length / T) per row, clipped to the table width — pages past
    the prefix never enter the compute body."""
    rng = np.random.default_rng(30)
    rows, n_pages, pool = 4, PPS, 16
    kp, vp = _quant_pool(rng, pool), _quant_pool(rng, pool)
    q = jnp.asarray(rng.standard_normal((rows, 1, 2, 16)), jnp.float32)
    table = _identity_table(rows, n_pages)
    lengths = np.asarray([1, PAGE_T, PAGE_T + 1, n_pages * PAGE_T],
                         np.int32)
    _, vis = paged_attention_probe(q, kp, vp, jnp.asarray(table),
                                   jnp.asarray(lengths), interpret=True)
    expect = np.minimum(np.asarray(paged_attention_pages(lengths, PAGE_T)),
                        n_pages)
    np.testing.assert_array_equal(np.asarray(vis),
                                  np.tile(expect[:, None], (1, 2)))


def test_kernel_cost_twin_flops_track_active_pages():
    """The analytic twin mirrors the kernel's economics: FLOPs scale
    with ACTIVE pages (the skip predicate), HBM bytes with ALL fetched
    page tiles (the pipeline DMAs skipped blocks too)."""
    short = paged_attention_cost([PAGE_T] * 4, PPS, PAGE_T, 4, 8)
    full = paged_attention_cost([PPS * PAGE_T] * 4, PPS, PAGE_T, 4, 8)
    assert full["flops"] == PPS * short["flops"]
    assert full["hbm_bytes"] == short["hbm_bytes"]
    # truncating the table width IS the bytes lever
    narrow = paged_attention_cost([PAGE_T] * 4, 1, PAGE_T, 4, 8)
    assert narrow["hbm_bytes"] < short["hbm_bytes"]


# -- byte accounting + journal events ----------------------------------------

def test_byte_accounting_charges_pinned_pages(mesh8):
    model, params = init_lm_for_serving("causal_tiny", seed=0, **PAGED_KW)
    grid = default_decode_grid(model, max_slots=MAX_SLOTS)
    eng = DecodeEngine(model, params, mesh8, model_name="causal_tiny",
                       grid=grid)

    def expect(pinned_pages):
        return (eng._params_bytes
                + eng._page_bytes * (PPS + pinned_pages)) // mesh8.size

    assert eng.cache.base_bytes == expect(0)
    assert eng.try_reserve(0, 2 * PAGE_T + 1)  # 3 pages
    assert eng.cache.base_bytes == expect(3)
    assert eng.kv_stats()["kv_bytes_pinned"] == 3 * eng._page_bytes
    eng.release_slot(0)
    assert eng.cache.base_bytes == expect(0)
    # the dense twin charges its whole stripe up front — the bug this
    # accounting replaces
    dense_base = (eng._params_bytes
                  + sum(int(np.prod(a.shape)) * a.dtype.itemsize
                        for a in jax.tree.leaves(
                            CausalLMTiny(**LM_KW).init_cache(grid.rows)))
                  ) // mesh8.size
    assert eng.cache.base_bytes < dense_base


def test_page_events_journaled(mesh8, tmp_path):
    model, params = init_lm_for_serving("causal_tiny", seed=0, **PAGED_KW)
    grid = default_decode_grid(model, max_slots=MAX_SLOTS)
    eng = DecodeEngine(model, params, mesh8, model_name="causal_tiny",
                       grid=grid)
    path = tmp_path / "journal.jsonl"
    prev = events.set_journal(events.RunJournal(path))
    try:
        eng.try_reserve(2, PAGE_T + 1)
        eng.release_slot(2)
    finally:
        events.set_journal(prev)
    recs = {r["event"]: r for r in events.tail_journal(path)}
    assert recs["kv_page_alloc"]["slot"] == 2
    assert recs["kv_page_alloc"]["pages"] == 2
    assert recs["kv_page_reclaim"]["pages"] == 2


# -- engine construction guards ----------------------------------------------

def test_engine_rejects_mismatched_grid(mesh8):
    model, params = init_lm_for_serving("causal_tiny", seed=0, **PAGED_KW)
    dense_grid = DecodeGrid(max_slots=MAX_SLOTS, max_seq=LM_KW["max_seq"],
                            prompt_buckets=(LM_KW["max_seq"],),
                            admit_buckets=(MAX_SLOTS,))
    with pytest.raises(ValueError, match="decode_page_buckets"):
        DecodeEngine(model, params, mesh8, grid=dense_grid)
    with pytest.raises(ValueError, match="pool"):
        DecodeEngine(model, params, mesh8,
                     grid=default_decode_grid(model, max_slots=MAX_SLOTS),
                     num_pages=PPS)  # scratch only, no slot capacity
