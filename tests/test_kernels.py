"""Parity + structure gates for the serve-hot-path Pallas kernels.

Every kernel in ops/pallas runs here in interpret mode against its
pure-XLA reference:

- `quant_matmul` (fused int8 dequant-matmul) vs `q_dot`'s materialize
  path — 2-D/3-D activations, the stacked scan (`[L, D, 3D]` with
  `[L, 1, 3D]` scales) and MoE (`[E, D, H]` with `[E, 1, H]` scales)
  leaf layouts, the per-tensor fallback mode, and bf16 activations.
- `masked_flash_attention` (variable-length key-prefix flash) vs the
  `-1e30` pre-softmax einsum — every zoo (batch, seq) bucket shape,
  bf16 tolerances, forward AND backward (custom VJP), plus the
  STRUCTURAL gate: the kernel's own visit counter must equal
  ceil(length / block_k) per row, i.e. attention work scales with real
  token length, not bucket length.
- `fused_adam_clip_wd_update` (one-pass clip + Adam + decoupled wd) vs
  the chained `clip_by_global_norm >> adamw` optimizer — and the
  bit-identity of the off-path (`fused_adamw(wd=0, clip=None)` ==
  `adam(fused=True)`).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dist_mnist_tpu import optim
from dist_mnist_tpu.ops import quant
from dist_mnist_tpu.ops.pallas.flash_attention import (
    masked_flash_attention,
    masked_flash_attention_probe,
    masked_flash_flops,
    masked_key_blocks,
)
from dist_mnist_tpu.ops.pallas.quant_matmul import quant_matmul


def _rel_err(got, want):
    got = jnp.asarray(got, jnp.float32)
    want = jnp.asarray(want, jnp.float32)
    return float(jnp.max(jnp.abs(got - want))) / (
        float(jnp.max(jnp.abs(want))) + 1e-12)


# -- fused int8 dequant-matmul ------------------------------------------------


def _quantized(rng, d, h, mode="channel"):
    w = jnp.asarray(rng.standard_normal((d, h)), jnp.float32)
    if mode == "channel":
        return quant.quantize(w)
    scale = jnp.broadcast_to(jnp.max(jnp.abs(w)) / 127.0,
                             (1, h)).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / scale), -127, 127).astype(jnp.int8)
    return quant.QuantizedArray(q, scale, "tensor")


@pytest.mark.parametrize("lead,dtype,mode,tol", [
    ((32,), jnp.float32, "channel", 2e-5),
    ((4, 17), jnp.float32, "channel", 2e-5),   # odd rows, 3-D activations
    ((32,), jnp.float32, "tensor", 2e-5),      # per-tensor fallback layout
    ((32,), jnp.bfloat16, "channel", 2e-2),
])
def test_quant_matmul_matches_materialize(lead, dtype, mode, tol):
    rng = np.random.default_rng(0)
    d, h = 48, 200  # non-multiples of the 128 tile on purpose
    w_q = _quantized(rng, d, h, mode)
    x = jnp.asarray(rng.standard_normal((*lead, d)), dtype)
    got = quant_matmul(x, w_q.q, w_q.scale)
    want = x @ quant.dequantize(w_q, x.dtype)
    assert got.shape == want.shape and got.dtype == x.dtype
    assert _rel_err(got, want) < tol


def test_quant_matmul_scan_stacked_leaves():
    """The ViT scan layout: [L, D, 3D] kernels with [L, 1, 3D] scales,
    sliced layer-by-layer by lax.scan before reaching the kernel."""
    rng = np.random.default_rng(1)
    layers, d = 3, 32
    w = jnp.asarray(rng.standard_normal((layers, d, 3 * d)), jnp.float32)
    qa = quant.quantize(w)
    assert qa.q.shape == (layers, d, 3 * d)
    assert qa.scale.shape == (layers, 1, 3 * d)
    x = jnp.asarray(rng.standard_normal((8, d)), jnp.float32)

    def body(carry, leaf):
        q, s = leaf
        return carry, quant_matmul(carry, q, s)

    _, got = jax.lax.scan(body, x, (qa.q, qa.scale))
    want = jnp.einsum("md,ldh->lmh", x, quant.dequantize(qa))
    assert _rel_err(got, want) < 2e-5


def test_quant_matmul_moe_stacked_leaves_vmap():
    """The MoE layout: [E, D, H] expert stacks with [E, 1, H] scales,
    batched over experts by vmap (the moe dense-oracle path)."""
    rng = np.random.default_rng(2)
    e, d, h = 4, 32, 64
    w = jnp.asarray(rng.standard_normal((e, d, h)), jnp.float32)
    qa = quant.quantize(w)
    assert qa.scale.shape == (e, 1, h)
    toks = jnp.asarray(rng.standard_normal((e, 6, d)), jnp.float32)
    got = jax.vmap(quant_matmul)(toks, qa.q, qa.scale)
    want = jnp.einsum("emd,edh->emh", toks, quant.dequantize(qa))
    assert _rel_err(got, want) < 2e-5


def test_q_dot_and_q_einsum_dispatch(monkeypatch):
    """`q_dot`/`q_einsum` route 2-D quantized weights through the Pallas
    kernel when FUSED_MATMUL forces it, and keep the XLA materialize path
    otherwise — same numbers either way (that's the whole contract)."""
    rng = np.random.default_rng(3)
    w_q = _quantized(rng, 48, 72)
    x = jnp.asarray(rng.standard_normal((5, 48)), jnp.float32)
    monkeypatch.setattr(quant, "FUSED_MATMUL", "xla")
    ref_dot = quant.q_dot(x, w_q)
    ref_ein = quant.q_einsum("md,dh->mh", x, w_q)
    monkeypatch.setattr(quant, "FUSED_MATMUL", "pallas")
    via_dot = quant.q_dot(x, w_q)
    via_ein = quant.q_einsum("md,dh->mh", x, w_q)
    assert bool(jnp.array_equal(via_dot,
                                quant_matmul(x, w_q.q, w_q.scale)))
    assert bool(jnp.array_equal(via_ein, via_dot))
    assert _rel_err(via_dot, ref_dot) < 2e-5
    assert _rel_err(via_ein, ref_ein) < 2e-5
    # float (non-quantized) weights are a passthrough matmul in any mode
    w_f = jnp.asarray(rng.standard_normal((48, 72)), jnp.float32)
    assert bool(jnp.array_equal(quant.q_dot(x, w_f), x @ w_f))


def test_q_einsum_non_matmul_spec_stays_on_xla(monkeypatch):
    """Specs the kernel cannot express (transposed contraction) must fall
    back to the einsum-on-dequantized path even in forced-pallas mode."""
    rng = np.random.default_rng(4)
    w_q = _quantized(rng, 48, 72)
    x = jnp.asarray(rng.standard_normal((5, 72)), jnp.float32)
    monkeypatch.setattr(quant, "FUSED_MATMUL", "pallas")
    got = quant.q_einsum("mh,dh->md", x, w_q)
    want = jnp.einsum("mh,dh->md", x, quant.dequantize(w_q, x.dtype))
    assert bool(jnp.array_equal(got, want))


# -- masked variable-length flash ---------------------------------------------


def _ref_attention(q, k, v, lengths):
    """The -1e30 pre-softmax einsum (ops/nn.dot_product_attention's mask
    semantics) on a key-prefix mask."""
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    mask = jnp.arange(k.shape[1])[None, :] < lengths[:, None]
    logits = jnp.where(mask[:, None, None, :], logits, jnp.float32(-1e30))
    w = jax.nn.softmax(logits, -1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


# every zoo (batch, seq) bucket shape: the engine's batch ladder {8, 16}
# x the default height ladder 4/8/16 -> 4/8/16 patch tokens + CLS
ZOO_BUCKETS = [(b, s) for b in (8, 16) for s in (5, 9, 17)]


@pytest.mark.parametrize("batch,seq", ZOO_BUCKETS)
def test_masked_flash_matches_einsum_zoo_buckets(batch, seq):
    rng = np.random.default_rng(seq * 100 + batch)
    h, dh = 2, 8
    q = jnp.asarray(rng.standard_normal((batch, seq, h, dh)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((batch, seq, h, dh)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((batch, seq, h, dh)), jnp.float32)
    lengths = jnp.asarray(rng.integers(1, seq + 1, size=(batch,)),
                          jnp.int32)
    got = masked_flash_attention(q, k, v, lengths)
    want = _ref_attention(q, k, v, lengths)
    assert _rel_err(got, want) < 2e-5


def test_masked_flash_bf16_tolerance():
    rng = np.random.default_rng(7)
    b, s, h, dh = 4, 17, 2, 8
    mk = lambda: jnp.asarray(rng.standard_normal((b, s, h, dh)),
                             jnp.bfloat16)
    q, k, v = mk(), mk(), mk()
    lengths = jnp.asarray([1, 5, 9, 17], jnp.int32)
    got = masked_flash_attention(q, k, v, lengths)
    want = _ref_attention(q, k, v, lengths)
    assert got.dtype == jnp.bfloat16
    # bf16 has ~3 decimal digits; both paths round differently
    assert _rel_err(got, want) < 2e-2


def test_masked_flash_backward_matches_einsum():
    rng = np.random.default_rng(8)
    b, s, h, dh = 2, 300, 2, 8  # two key blocks at block_k=256-pad... 128*3
    q = jnp.asarray(rng.standard_normal((b, s, h, dh)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, s, h, dh)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, s, h, dh)), jnp.float32)
    lengths = jnp.asarray([120, 300], jnp.int32)

    def loss(fn):
        return jax.grad(
            lambda q, k, v: jnp.sum(jnp.sin(fn(q, k, v, lengths))),
            (0, 1, 2))(q, k, v)

    gk = loss(lambda *a: masked_flash_attention(*a, block_k=128))
    gr = loss(_ref_attention)
    for a, b_ in zip(gk, gr):
        assert _rel_err(a, b_) < 2e-5
    # gradients through masked-out keys are exactly zero (row 0 attends
    # only its first 120 keys)
    assert bool(jnp.all(gk[1][0, 120:] == 0.0))
    assert bool(jnp.all(gk[2][0, 120:] == 0.0))


def test_masked_flash_work_scales_with_length_not_bucket():
    """The structural acceptance gate: the kernel's in-kernel visit
    counter — incremented inside the same `pl.when` that guards ALL the
    tile math — equals ceil(length/block_k), strictly below the bucket's
    block count for short rows; the analytic FLOPs follow the same
    expression."""
    rng = np.random.default_rng(9)
    b, s, h, dh = 4, 512, 2, 8
    block_k = 128
    mk = lambda: jnp.asarray(rng.standard_normal((b, s, h, dh)),
                             jnp.float32)
    lengths = jnp.asarray([64, 128, 200, 512], jnp.int32)
    _, visits = masked_flash_attention_probe(mk(), mk(), mk(), lengths,
                                             block_k=block_k)
    got_blocks = np.asarray(visits[:, 0, 0], np.int64)
    want_blocks = np.asarray(masked_key_blocks(lengths, block_k))
    assert got_blocks.tolist() == want_blocks.tolist() == [1, 1, 2, 4]
    bucket_blocks = s // block_k
    assert (got_blocks[:3] < bucket_blocks).all()  # short rows skip work
    # every head/query-row of a batch row sees the same count
    assert bool(jnp.all(visits == visits[:, :1, :1]))
    # reported FLOPs use the same active-block expression -> scale with
    # real token length, not the bucket ceiling
    flops = masked_flash_flops(lengths, s, h, dh, block_k)
    full = 2 * 2 * s * dh * h * s * b
    assert flops == pytest.approx(full * (1 + 1 + 2 + 4) / (4 * 4))


def test_masked_flash_rejects_bad_lengths_shape():
    x = jnp.zeros((2, 8, 1, 8))
    with pytest.raises(ValueError, match="lengths"):
        masked_flash_attention(x, x, x, jnp.zeros((3,), jnp.int32))


# -- one-pass fused clip + Adam + decoupled wd --------------------------------


def _tree(rng):
    return {"w": jnp.asarray(rng.standard_normal((130, 257)), jnp.float32),
            "b": jnp.asarray(rng.standard_normal((7,)), jnp.float32)}


def test_fused_adamw_matches_chained_clip_adamw():
    rng = np.random.default_rng(10)
    params = _tree(rng)
    grads = jax.tree.map(
        lambda p: 3.0 * jnp.asarray(rng.standard_normal(p.shape),
                                    jnp.float32), params)
    ref = optim.chain(optim.clip_by_global_norm(0.5),
                      optim.adamw(1e-3, weight_decay=0.01))
    fused = optim.fused_adamw(1e-3, weight_decay=0.01, clip_norm=0.5)
    s_r, s_f = ref.init(params), fused.init(params)
    p_r, p_f = params, params
    for _ in range(3):
        u_r, s_r = ref.update(grads, s_r, p_r)
        u_f, s_f = fused.update(grads, s_f, p_f)
        p_r = optim.apply_updates(p_r, u_r)
        p_f = optim.apply_updates(p_f, u_f)
    for a, b in zip(jax.tree.leaves(p_r), jax.tree.leaves(p_f)):
        assert _rel_err(a, b) < 1e-6
    # slot trees stay plain containers (checkpoint-manager contract)
    assert set(s_f) == {"m", "v", "count"}


def test_fused_adamw_off_path_bit_identical():
    """wd=0 + no clip routes to the EXACT original fused kernel: the
    one-pass variant must not perturb the plain-Adam path by even 1 ulp."""
    rng = np.random.default_rng(11)
    params = _tree(rng)
    grads = jax.tree.map(
        lambda p: jnp.asarray(rng.standard_normal(p.shape), jnp.float32),
        params)
    a = optim.adam(1e-3, fused=True)
    f = optim.fused_adamw(1e-3, weight_decay=0.0, clip_norm=None)
    u_a, s_a = a.update(grads, a.init(params), params)
    u_f, s_f = f.update(grads, f.init(params), params)
    for ta, tf in ((u_a, u_f), (s_a["m"], s_f["m"]), (s_a["v"], s_f["v"])):
        for x, y in zip(jax.tree.leaves(ta), jax.tree.leaves(tf)):
            assert bool(jnp.array_equal(x, y))


def test_fused_adamw_wd_only_matches_adamw():
    """clip_norm=None + wd>0 exercises the clip_scale=1 kernel path."""
    rng = np.random.default_rng(12)
    params = _tree(rng)
    grads = jax.tree.map(
        lambda p: jnp.asarray(rng.standard_normal(p.shape), jnp.float32),
        params)
    ref = optim.adamw(1e-3, weight_decay=0.02)
    fused = optim.fused_adamw(1e-3, weight_decay=0.02)
    u_r, _ = ref.update(grads, ref.init(params), params)
    u_f, _ = fused.update(grads, fused.init(params), params)
    for a, b in zip(jax.tree.leaves(u_r), jax.tree.leaves(u_f)):
        assert _rel_err(a, b) < 1e-6


# -- model wiring -------------------------------------------------------------


def test_vit_masked_flash_matches_masked_xla():
    """The serve path: a maskable ViT with attention_impl='flash' runs
    the variable-length kernel and agrees with the xla einsum engine on
    the same sub-native masked batch."""
    from dist_mnist_tpu.models.registry import get_model
    from dist_mnist_tpu.serve.zoo import supports_mask

    common = dict(depth=1, dim=16, heads=2, patch=4, pool="mean",
                  compute_dtype=jnp.float32)
    vx = get_model("vit_tiny", attention_impl="xla", **common)
    vf = get_model("vit_tiny", attention_impl="flash", **common)
    assert supports_mask(vf)
    x = jnp.asarray(np.random.default_rng(13).standard_normal(
        (2, 16, 16, 3)), jnp.float32)
    p, s = vx.init(jax.random.PRNGKey(0), x)
    n_tok = (16 // 4) * (16 // 4)
    mask = np.ones((2, n_tok), bool)
    mask[1, 4:] = False  # sample 1: one real patch row
    ox, _ = vx.apply(p, s, x, mask=jnp.asarray(mask))
    of, _ = vf.apply(p, s, x, mask=jnp.asarray(mask))
    assert _rel_err(of, ox) < 2e-5
    assert bool(jnp.all(jnp.argmax(ox, -1) == jnp.argmax(of, -1)))


def test_causal_lm_flash_decode_matches_xla():
    """attention_impl='flash' decode (lengths = pos + 1 against the
    cache) tracks the bit-exact xla path within fp tolerance and agrees
    on every sampled token."""
    from dist_mnist_tpu.models.causal_lm import CausalLMTiny

    mx = CausalLMTiny()
    mf = CausalLMTiny(attention_impl="flash")
    params, _ = mx.init(jax.random.PRNGKey(1))
    cx, cf = mx.init_cache(4), mf.init_cache(4)
    toks = jnp.asarray(np.random.default_rng(14).integers(
        0, 256, size=(4, 16)))
    lengths = jnp.asarray([16, 9, 4, 12])
    last_x, cx = mx.prefill(params, cx, toks, jnp.arange(4), lengths)
    last_f, cf = mf.prefill(params, cf, toks, jnp.arange(4), lengths)
    # prefill keeps the xla path -> bit-identical
    assert bool(jnp.array_equal(last_x, last_f))
    pos, tok = lengths, jnp.argmax(last_x, -1)
    for _ in range(4):
        lx, cx = mx.decode_step(params, cx, tok, pos)
        lf, cf = mf.decode_step(params, cf, tok, pos)
        assert _rel_err(lf, lx) < 1e-5
        assert bool(jnp.all(jnp.argmax(lx, -1) == jnp.argmax(lf, -1)))
        tok, pos = jnp.argmax(lx, -1), pos + 1


def test_causal_lm_rejects_unknown_attention_impl():
    from dist_mnist_tpu.models.causal_lm import CausalLMTiny

    with pytest.raises(ValueError, match="attention_impl"):
        CausalLMTiny(attention_impl="ring").init(jax.random.PRNGKey(0))
