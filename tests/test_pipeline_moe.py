"""Pipeline (GPipe over `pipe`) and expert-parallel MoE tests.

Strategy (SURVEY.md §4): the numeric oracle is the same computation run
without the mesh — the pipeline must equal sequentially applying the
stages; the distributed MoE must equal the dense all-experts-local oracle
when capacity is generous enough that no token is dropped.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dist_mnist_tpu.cluster.mesh import MeshSpec, make_mesh
from dist_mnist_tpu.parallel.moe import (
    init_moe,
    moe_ffn,
    moe_ffn_dense,
)
from dist_mnist_tpu.parallel.pipeline import (
    pipeline_apply,
    stack_stage_params,
)


def _stage_fn(params, x):
    return jax.nn.relu(x @ params["w"] + params["b"])


def _make_stages(key, n_stages, dim):
    keys = jax.random.split(key, n_stages)
    return [
        {
            "w": jax.random.normal(k, (dim, dim)) / np.sqrt(dim),
            "b": jnp.zeros((dim,)),
        }
        for k in keys
    ]


@pytest.fixture(scope="module")
def pipe_mesh():
    return make_mesh(MeshSpec(data=2, pipe=4))


class TestPipeline:
    @pytest.mark.slow
    def test_matches_sequential(self, pipe_mesh):
        dim, batch, n_stages = 16, 32, 4
        stages = _make_stages(jax.random.PRNGKey(0), n_stages, dim)
        x = jax.random.normal(jax.random.PRNGKey(1), (batch, dim))

        expected = x
        for p in stages:
            expected = _stage_fn(p, expected)

        stacked = stack_stage_params(stages)
        got = pipeline_apply(_stage_fn, stacked, x, num_microbatches=8,
                             mesh=pipe_mesh)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.slow
    def test_differentiable(self, pipe_mesh):
        """grad flows through the ppermute schedule (the PP backward)."""
        dim, batch, n_stages = 8, 16, 4
        stages = _make_stages(jax.random.PRNGKey(2), n_stages, dim)
        stacked = stack_stage_params(stages)
        x = jax.random.normal(jax.random.PRNGKey(3), (batch, dim))

        def loss(stacked_params):
            y = pipeline_apply(_stage_fn, stacked_params, x,
                               num_microbatches=4, mesh=pipe_mesh)
            return jnp.sum(y**2)

        def loss_seq(params_list):
            y = x
            for p in params_list:
                y = _stage_fn(p, y)
            return jnp.sum(y**2)

        g_pipe = jax.grad(loss)(stacked)
        g_seq = jax.grad(loss_seq)(stages)
        g_seq_stacked = stack_stage_params(g_seq)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            ),
            g_pipe,
            g_seq_stacked,
        )

    @pytest.mark.slow
    def test_circular_matches_sequential(self, pipe_mesh):
        """The interleaved schedule (v chunks per rank, stage c*S+s on rank
        s) must equal sequential application exactly — including the wrap
        hop where retire and ingest share one ring transfer."""
        dim, batch, n_stages, v = 16, 32, 4, 2
        stages = _make_stages(jax.random.PRNGKey(6), n_stages * v, dim)
        x = jax.random.normal(jax.random.PRNGKey(7), (batch, dim))

        expected = x
        for p in stages:
            expected = _stage_fn(p, expected)

        stacked = stack_stage_params(stages)
        got = pipeline_apply(_stage_fn, stacked, x, num_microbatches=8,
                             mesh=pipe_mesh, circular_chunks=v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=1e-5, atol=1e-5)

    @pytest.mark.slow
    def test_circular_differentiable(self, pipe_mesh):
        dim, batch, n_stages, v = 8, 16, 4, 2
        stages = _make_stages(jax.random.PRNGKey(8), n_stages * v, dim)
        stacked = stack_stage_params(stages)
        x = jax.random.normal(jax.random.PRNGKey(9), (batch, dim))

        def loss(sp):
            y = pipeline_apply(_stage_fn, sp, x, num_microbatches=4,
                               mesh=pipe_mesh, circular_chunks=v)
            return jnp.sum(y**2)

        def loss_seq(params_list):
            y = x
            for p in params_list:
                y = _stage_fn(p, y)
            return jnp.sum(y**2)

        g_pipe = jax.grad(loss)(stacked)
        g_seq = stack_stage_params(jax.grad(loss_seq)(stages))
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            ),
            g_pipe, g_seq,
        )

    def test_circular_guards(self, pipe_mesh):
        stages = _make_stages(jax.random.PRNGKey(10), 8, 8)
        stacked = stack_stage_params(stages)
        # microbatch count not divisible by rank count
        with pytest.raises(ValueError, match="rank-width groups"):
            pipeline_apply(_stage_fn, stacked, jnp.ones((18, 8)), 6,
                           pipe_mesh, circular_chunks=2)
        # wrong stage count for S*v
        with pytest.raises(ValueError, match="circular_chunks"):
            pipeline_apply(_stage_fn, stacked, jnp.ones((16, 8)), 4,
                           pipe_mesh, circular_chunks=3)

    def test_under_jit(self, pipe_mesh):
        dim, batch = 8, 16
        stages = _make_stages(jax.random.PRNGKey(4), 4, dim)
        stacked = stack_stage_params(stages)
        x = jnp.ones((batch, dim))
        f = jax.jit(
            lambda p, x: pipeline_apply(_stage_fn, p, x, 4, pipe_mesh)
        )
        out = f(stacked, x)
        assert out.shape == (batch, dim)
        assert bool(jnp.all(jnp.isfinite(out)))

    def test_bad_shapes_raise(self, pipe_mesh):
        stages = _make_stages(jax.random.PRNGKey(5), 2, 8)  # != pipe size 4
        stacked = stack_stage_params(stages)
        with pytest.raises(ValueError, match="pipe axis size"):
            pipeline_apply(_stage_fn, stacked, jnp.ones((8, 8)), 4, pipe_mesh)
        stages4 = _make_stages(jax.random.PRNGKey(5), 4, 8)
        with pytest.raises(ValueError, match="microbatches"):
            pipeline_apply(_stage_fn, stack_stage_params(stages4),
                           jnp.ones((9, 8)), 4, pipe_mesh)


@pytest.fixture(scope="module")
def ep_mesh():
    return make_mesh(MeshSpec(data=2, model=4))


class TestMoE:
    @pytest.mark.slow
    def test_dense_routes_and_shapes(self):
        params = init_moe(jax.random.PRNGKey(0), dim=16, hidden=32,
                          n_experts=4)
        x = jax.random.normal(jax.random.PRNGKey(1), (64, 16))
        out, aux, stats = moe_ffn_dense(params, x)
        assert out.shape == x.shape
        assert np.isfinite(float(aux))
        # aux of a perfectly uniform router is 1.0; any router is >= 1 - eps
        assert float(aux) >= 0.99
        assert 0.0 <= float(stats["drop_fraction"]) <= 1.0
        assert stats["expert_load"].shape == (4,)

    @pytest.mark.slow
    def test_distributed_matches_dense(self, ep_mesh):
        """With capacity >= all tokens nothing is dropped, so EP dispatch
        must reproduce the dense oracle bit-for-bit (same expert math)."""
        dim, tokens = 16, 64
        params = init_moe(jax.random.PRNGKey(2), dim=dim, hidden=32,
                          n_experts=4)
        x = jax.random.normal(jax.random.PRNGKey(3), (tokens, dim))
        dense_out, dense_aux, dense_stats = moe_ffn_dense(
            params, x, capacity_factor=4.0)
        ep_out, ep_aux, ep_stats = moe_ffn(params, x, ep_mesh,
                                           capacity_factor=4.0)
        # generous capacity: neither path drops anything, and both SAY so
        assert float(dense_stats["drop_fraction"]) == 0.0
        assert float(ep_stats["drop_fraction"]) == 0.0
        np.testing.assert_allclose(
            np.asarray(ep_out), np.asarray(dense_out), rtol=1e-5, atol=1e-5
        )
        # aux is built from globally pmean'd router stats, so it must equal
        # the dense oracle's global value, not a per-shard approximation
        np.testing.assert_allclose(
            float(ep_aux), float(dense_aux), rtol=1e-5
        )

    @pytest.mark.slow
    def test_distributed_differentiable(self, ep_mesh):
        """grad flows through both all_to_alls (EP backward)."""
        params = init_moe(jax.random.PRNGKey(4), dim=8, hidden=16,
                          n_experts=4)
        x = jax.random.normal(jax.random.PRNGKey(5), (32, 8))

        def loss(p):
            out, aux, _ = moe_ffn(p, x, ep_mesh, capacity_factor=2.0)
            return jnp.sum(out**2) + 0.01 * aux

        g = jax.grad(loss)(params)
        for leaf in jax.tree.leaves(g):
            assert bool(jnp.all(jnp.isfinite(leaf)))
        # every expert sharded-weight leaf must receive signal
        assert float(jnp.sum(jnp.abs(g["w1"]))) > 0
        assert float(jnp.sum(jnp.abs(g["gate"]))) > 0

    @pytest.mark.slow
    def test_capacity_drops_tokens(self):
        """Switch semantics: over-capacity tokens contribute zero output."""
        params = init_moe(jax.random.PRNGKey(6), dim=8, hidden=16,
                          n_experts=2)
        # force every token to expert 0: all-positive tokens x an extreme
        # gate (score_0 = 10*sum(x) > 0 > -10*sum(x) = score_1)
        params["gate"] = jnp.array(
            np.stack([np.full((8,), 10.0), np.full((8,), -10.0)], axis=1)
        )
        x = jnp.abs(jax.random.normal(jax.random.PRNGKey(7), (16, 8))) + 0.1
        out, _, stats = moe_ffn_dense(params, x, capacity_factor=0.5)
        # capacity = ceil(16/2) * 0.5 = 4 -> tokens 4.. dropped
        dropped = np.asarray(out[4:])
        np.testing.assert_allclose(dropped, np.zeros_like(dropped), atol=0)
        # ...and the health stats PIN the drop: 12 of 16 assignments lost,
        # expert 0's queue full, expert 1 idle (VERDICT r3 weak 5)
        np.testing.assert_allclose(float(stats["drop_fraction"]), 12 / 16)
        np.testing.assert_allclose(np.asarray(stats["expert_load"]),
                                   [1.0, 0.0])

    @pytest.mark.slow
    def test_top2_distributed_matches_dense(self, ep_mesh):
        """GShard-style top-2: EP dispatch == dense oracle with generous
        capacity, and the combine weights renormalize over the chosen two
        (output is a convex mix of two expert outputs per token)."""
        dim, tokens = 16, 64
        params = init_moe(jax.random.PRNGKey(9), dim=dim, hidden=32,
                          n_experts=4)
        x = jax.random.normal(jax.random.PRNGKey(10), (tokens, dim))
        dense_out, dense_aux, dense_stats = moe_ffn_dense(
            params, x, capacity_factor=4.0, top_k=2)
        ep_out, ep_aux, ep_stats = moe_ffn(params, x, ep_mesh,
                                           capacity_factor=4.0, top_k=2)
        np.testing.assert_allclose(np.asarray(ep_out), np.asarray(dense_out),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(float(ep_aux), float(dense_aux), rtol=1e-5)
        assert float(dense_stats["drop_fraction"]) == 0.0
        # top-2 routes 2 assignments per token
        cap = 2 * 4 * int(np.ceil(tokens / 4))  # C per expert at cf=4, k=2
        assert float(jnp.sum(dense_stats["expert_load"])) * cap == pytest.approx(
            2 * tokens)

    @pytest.mark.slow
    def test_tight_capacity_divergence_quantified(self):
        """capacity_factor=0.5 vs the no-drop oracle: the divergence is
        real but bounded — exactly the degradation the drop_fraction metric
        exists to surface (a silent-drop regression would show here)."""
        params = init_moe(jax.random.PRNGKey(11), dim=16, hidden=32,
                          n_experts=4)
        x = jax.random.normal(jax.random.PRNGKey(12), (64, 16))
        full, _, full_stats = moe_ffn_dense(params, x, capacity_factor=4.0)
        tight, _, tight_stats = moe_ffn_dense(params, x, capacity_factor=0.5)
        assert float(full_stats["drop_fraction"]) == 0.0
        drop = float(tight_stats["drop_fraction"])
        assert drop > 0.0  # tight capacity really drops
        # dropped tokens output EXACTLY zero; their fraction is what the
        # metric reports (kept rows match the oracle up to reduction-order
        # float noise — the combine contraction's slot dim differs)
        zero_rows = np.mean(np.abs(np.asarray(tight)).max(axis=-1) == 0.0)
        assert zero_rows == pytest.approx(drop, abs=1e-6)
        kept = np.abs(np.asarray(tight)).max(axis=-1) > 0.0
        np.testing.assert_allclose(np.asarray(tight)[kept],
                                   np.asarray(full)[kept],
                                   rtol=2e-5, atol=2e-6)

    @pytest.mark.slow
    def test_expert_count_mismatch_raises(self, ep_mesh):
        params = init_moe(jax.random.PRNGKey(8), dim=8, hidden=16,
                          n_experts=2)  # != model axis 4
        with pytest.raises(ValueError, match="n_experts"):
            moe_ffn(params, jnp.ones((32, 8)), ep_mesh)


class TestMoEInViT:
    """MoE selected FROM THE MODEL (`ViTTiny(mlp_impl="moe")`) — the
    through-model wiring, mirroring the ulysses-in-model coverage."""

    KW = dict(depth=1, dim=32, heads=4, patch=8, pool="mean",
              mlp_impl="moe", n_experts=2, moe_capacity_factor=4.0,
              compute_dtype=jnp.float32)

    def _data(self):
        rng = np.random.default_rng(7)
        x = jnp.asarray(rng.normal(size=(4, 32, 32, 3)), jnp.float32)
        y = jnp.asarray(rng.integers(0, 10, (4,)), jnp.int32)
        return x, y

    def test_ep_matches_dense_through_model(self):
        """Expert-parallel on a model=2 mesh == dense-local (no mesh) for
        the same params, when capacity is generous (nothing dropped)."""
        from dist_mnist_tpu.cluster.mesh import activate
        from dist_mnist_tpu.models import get_model

        model = get_model("vit_tiny", **self.KW)
        x, _ = self._data()
        params, state = model.init(jax.random.PRNGKey(0), x)
        dense_logits, dense_state = model.apply(params, state, x, train=False)

        mesh = make_mesh(MeshSpec(data=2, model=2))
        with activate(mesh):
            ep_logits, ep_state = jax.jit(
                lambda p: model.apply(p, state, x, train=False)
            )(params)
            jax.block_until_ready(ep_logits)
        np.testing.assert_allclose(np.asarray(dense_logits),
                                   np.asarray(ep_logits),
                                   rtol=2e-4, atol=2e-5)
        np.testing.assert_allclose(float(dense_state["moe_aux"]),
                                   float(ep_state["moe_aux"]), rtol=2e-4)
        # EP engagement is a step-visible fact, not just a Python warning
        assert float(ep_state["moe_ep_engaged_metric"]) == 1.0
        assert float(dense_state["moe_ep_engaged_metric"]) == 0.0

    def test_aux_loss_reaches_gradients(self, mesh_tp):
        """The load-balance aux rides model_state into the train loss:
        router gate weights get gradients (pure CE would starve them of
        the balance signal) and the step runs on an expert mesh."""
        from dist_mnist_tpu import optim
        from dist_mnist_tpu.cluster.mesh import activate
        from dist_mnist_tpu.data.pipeline import shard_batch
        from dist_mnist_tpu.models import get_model
        from dist_mnist_tpu.parallel.sharding import shard_train_state
        from dist_mnist_tpu.train import create_train_state, make_train_step

        model = get_model("vit_tiny", **self.KW)
        opt = optim.adam(1e-3)
        rng = np.random.default_rng(0)
        batch_np = {
            "image": rng.integers(0, 255, (16, 32, 32, 3), dtype=np.uint8),
            "label": rng.integers(0, 10, (16,), dtype=np.int32),
        }
        with activate(mesh_tp):
            state = create_train_state(model, opt, jax.random.PRNGKey(0),
                                       batch_np["image"][:1])
            state = shard_train_state(state, mesh_tp)
            step = make_train_step(model, opt, mesh_tp, donate=False)
            new_state, out = step(state, shard_batch(batch_np, mesh_tp))
        assert np.isfinite(float(out["loss"]))
        assert float(new_state.model_state["moe_aux"]) > 0
        gate_delta = np.abs(
            np.asarray(new_state.params["block0"]["moe"]["gate"])
            - np.asarray(state.params["block0"]["moe"]["gate"])
        ).max()
        w1_delta = np.abs(
            np.asarray(new_state.params["block0"]["moe"]["w1"])
            - np.asarray(state.params["block0"]["moe"]["w1"])
        ).max()
        assert gate_delta > 0 and w1_delta > 0

    def test_moe_scan_blocks_remat_composition(self, mesh_tp):
        """The ladder config's riskiest composition — shard_map (MoE)
        nested in lax.scan (scan_blocks) under jax.checkpoint (remat) on
        an expert mesh — compiles and trains at CI size."""
        from dist_mnist_tpu import optim
        from dist_mnist_tpu.cluster.mesh import activate
        from dist_mnist_tpu.data.pipeline import shard_batch
        from dist_mnist_tpu.models import get_model
        from dist_mnist_tpu.parallel.sharding import shard_train_state
        from dist_mnist_tpu.train import create_train_state, make_train_step

        model = get_model("vit_tiny", scan_blocks=True, depth=2, dim=32,
                          heads=4, patch=8, pool="mean", mlp_impl="moe",
                          n_experts=2, compute_dtype=jnp.float32)
        opt = optim.adam(1e-3)
        rng = np.random.default_rng(3)
        batch_np = {
            "image": rng.integers(0, 255, (16, 32, 32, 3), dtype=np.uint8),
            "label": rng.integers(0, 10, (16,), dtype=np.int32),
        }
        with activate(mesh_tp):
            state = create_train_state(model, opt, jax.random.PRNGKey(0),
                                       batch_np["image"][:1])
            state = shard_train_state(state, mesh_tp)
            step = make_train_step(model, opt, mesh_tp, donate=False,
                                   remat=True)
            batch = shard_batch(batch_np, mesh_tp)
            new_state, out = step(state, batch)
        assert np.isfinite(float(out["loss"]))
        assert float(new_state.model_state["moe_aux"]) > 0
        assert int(jax.device_get(new_state.step)) == 1


class TestPipelineInViT:
    """GPipe selected FROM THE MODEL (`ViTTiny(block_pipeline=N)`): the
    pipelined stack must equal the plain scanned stack numerically."""

    KW = dict(depth=4, dim=32, heads=4, patch=8, pool="mean",
              dropout_rate=0.0, scan_blocks=True,
              compute_dtype=jnp.float32)

    def test_pipelined_matches_scan(self):
        from dist_mnist_tpu.cluster.mesh import activate
        from dist_mnist_tpu.models import get_model

        plain = get_model("vit_tiny", **self.KW)
        piped = get_model("vit_tiny", block_pipeline=2,
                          pipeline_microbatches=2, **self.KW)
        rng = np.random.default_rng(11)
        x = jnp.asarray(rng.normal(size=(4, 32, 32, 3)), jnp.float32)
        params, state = plain.init(jax.random.PRNGKey(0), x)

        ref_logits, _ = plain.apply(params, state, x, train=False)
        # off any pipe mesh the SAME pipelined model falls back to the scan
        fb_logits, _ = piped.apply(params, state, x, train=False)
        np.testing.assert_allclose(np.asarray(ref_logits),
                                   np.asarray(fb_logits), rtol=1e-6)

        mesh = make_mesh(MeshSpec(data=2, pipe=2))
        with activate(mesh):
            pp_logits, _ = jax.jit(
                lambda p: piped.apply(p, state, x, train=False)
            )(params)
            jax.block_until_ready(pp_logits)
        np.testing.assert_allclose(np.asarray(ref_logits),
                                   np.asarray(pp_logits),
                                   rtol=2e-4, atol=2e-5)
        # circular schedule (2 ranks x 2 chunks of 1 block) == same logits
        circ = get_model("vit_tiny", block_pipeline=2, pipeline_circular=2,
                         pipeline_microbatches=4, **self.KW)
        with activate(mesh):
            c_logits, _ = jax.jit(
                lambda p: circ.apply(p, state, x, train=False)
            )(params)
            jax.block_until_ready(c_logits)
        np.testing.assert_allclose(np.asarray(ref_logits),
                                   np.asarray(c_logits),
                                   rtol=2e-4, atol=2e-5)

    def test_pipelined_grads_flow(self):
        from dist_mnist_tpu.cluster.mesh import activate
        from dist_mnist_tpu.models import get_model
        from dist_mnist_tpu.ops.losses import softmax_cross_entropy

        piped = get_model("vit_tiny", block_pipeline=2,
                          pipeline_microbatches=2, **self.KW)
        rng = np.random.default_rng(12)
        x = jnp.asarray(rng.normal(size=(4, 32, 32, 3)), jnp.float32)
        y = jnp.asarray(rng.integers(0, 10, (4,)), jnp.int32)
        params, state = piped.init(jax.random.PRNGKey(0), x)

        def loss(p):
            logits, _ = piped.apply(p, state, x, train=False)
            return softmax_cross_entropy(logits, y)

        mesh = make_mesh(MeshSpec(data=2, pipe=2))
        with activate(mesh):
            g = jax.jit(jax.grad(loss))(params)
            jax.block_until_ready(jax.tree.leaves(g)[0])
        # every stage's blocks received gradient (both pipe ranks learn)
        gb = np.asarray(jnp.abs(g["blocks"]["attn"]["qkv"]["w"]).sum(axis=(1, 2)))
        assert (gb > 0).all(), gb

    def test_pipeline_guards(self, caplog):
        from dist_mnist_tpu.cluster.mesh import activate
        from dist_mnist_tpu.models import get_model

        mesh = make_mesh(MeshSpec(data=2, pipe=2))
        rng = np.random.default_rng(13)
        x = jnp.asarray(rng.normal(size=(4, 32, 32, 3)), jnp.float32)
        # stage-count/pipe-axis MISMATCH falls back to the plain scan
        # (one model, any topology), loudly — and still computes correctly
        import logging

        model = get_model("vit_tiny", block_pipeline=4, **self.KW)
        params, state = model.init(jax.random.PRNGKey(0), x)
        ref, _ = model.apply(params, state, x, train=False)  # no mesh: scan
        with caplog.at_level(logging.WARNING,
                                   logger="dist_mnist_tpu.models.vit"):
            with activate(mesh):
                out, _ = model.apply(params, state, x, train=False)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(out),
                                   rtol=2e-4, atol=2e-5)
        assert any("pipe axis" in r.message for r in caplog.records)
        # block_pipeline=1 off any pipe mesh is just the scan (no KeyError)
        m1 = get_model("vit_tiny", block_pipeline=1, **self.KW)
        p1, s1 = m1.init(jax.random.PRNGKey(0), x)
        out1, _ = m1.apply(p1, s1, x, train=False)
        assert np.isfinite(np.asarray(out1)).all()


def _stage_fn_rng(params, x, key):
    """Stochastic stage: dropout-style bernoulli mask from the threaded
    key — the exact key stream is what's under test."""
    y = jax.nn.relu(x @ params["w"] + params["b"])
    keep = jax.random.bernoulli(key, 0.8, y.shape)
    return jnp.where(keep, y / 0.8, 0.0)


class TestPipelineRng:
    """rng threading (VERDICT r4 weak #5 / next #4): the schedule's
    per-(microbatch, global stage) key derivation must reproduce a
    sequential replay with the SAME folded keys, exactly."""

    def _sequential(self, stages, x, num_microbatches, base):
        base = jax.random.fold_in(base, 0)  # data-shard fold at data=1
        mbs = jnp.split(x, num_microbatches)
        outs = []
        for m, xm in enumerate(mbs):
            for g, p in enumerate(stages):
                key = jax.random.fold_in(
                    jax.random.fold_in(base, m), g)
                xm = _stage_fn_rng(p, xm, key)
            outs.append(xm)
        return jnp.concatenate(outs)

    # data=1: per-device bernoulli draws are shard-shaped, so exact replay
    # against a full-microbatch reference needs the batch unsharded (under
    # DP the masks are a different-but-i.i.d. stream — statistically
    # equivalent, covered by the determinism test below)
    def test_rng_matches_sequential(self):
        mesh = make_mesh(MeshSpec(data=1, pipe=4))
        dim, batch, n_stages = 16, 32, 4
        stages = _make_stages(jax.random.PRNGKey(0), n_stages, dim)
        stacked = stack_stage_params(stages)
        x = jax.random.normal(jax.random.PRNGKey(1), (batch, dim))
        base = jax.random.PRNGKey(42)
        expected = self._sequential(stages, x, 8, base)
        got = pipeline_apply(_stage_fn_rng, stacked, x, num_microbatches=8,
                             mesh=mesh, rng=base)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=1e-6, atol=1e-6)

    def test_circular_rng_matches_sequential(self):
        mesh = make_mesh(MeshSpec(data=1, pipe=4))
        dim, batch, n_stages, v = 16, 32, 4, 2
        stages = _make_stages(jax.random.PRNGKey(2), n_stages * v, dim)
        stacked = stack_stage_params(stages)
        x = jax.random.normal(jax.random.PRNGKey(3), (batch, dim))
        base = jax.random.PRNGKey(43)
        expected = self._sequential(stages, x, 8, base)
        got = pipeline_apply(_stage_fn_rng, stacked, x, num_microbatches=8,
                             mesh=mesh, circular_chunks=v, rng=base)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=1e-6, atol=1e-6)

    def test_dp_shards_draw_decorrelated_masks(self, pipe_mesh):
        """Each data rank folds its axis index into the key, so DP shards'
        dropout masks are NOT bit-identical (code-review r5: with a
        replicated key every rank drew the same shard-shaped mask)."""
        dim, batch = 16, 32
        stages = _make_stages(jax.random.PRNGKey(0), 4, dim)
        stacked = stack_stage_params(stages)
        x = jnp.ones((batch, dim))
        out = pipeline_apply(_stage_fn_rng, stacked, x, num_microbatches=4,
                             mesh=pipe_mesh, rng=jax.random.PRNGKey(42))
        # rows of one microbatch live half on data rank 0, half on rank 1;
        # identical inputs -> any difference comes from the masks
        mb = np.asarray(out[:8])  # first microbatch, mb=8, 4 rows per rank
        assert not np.array_equal(mb[:4], mb[4:])

    def test_pipelined_vit_trains_with_dropout(self):
        """The pp ladder config's model now trains with dropout like its
        siblings: same rng -> same logits (deterministic key schedule),
        train-mode != eval-mode, grads finite."""
        from dist_mnist_tpu.cluster.mesh import activate
        from dist_mnist_tpu.models import get_model
        from dist_mnist_tpu.ops.losses import softmax_cross_entropy

        kw = dict(depth=4, dim=32, heads=4, patch=8, pool="mean",
                  dropout_rate=0.3, scan_blocks=True,
                  compute_dtype=jnp.float32)
        piped = get_model("vit_tiny", block_pipeline=2,
                          pipeline_microbatches=2, **kw)
        rng = np.random.default_rng(13)
        x = jnp.asarray(rng.normal(size=(4, 32, 32, 3)), jnp.float32)
        y = jnp.asarray(rng.integers(0, 10, (4,)), jnp.int32)
        params, state = piped.init(jax.random.PRNGKey(0), x)
        mesh = make_mesh(MeshSpec(data=2, pipe=2))
        dk = jax.random.PRNGKey(7)
        with activate(mesh):
            run = jax.jit(lambda p, k: piped.apply(
                p, state, x, train=True, rng=k)[0])
            a = run(params, dk)
            b = run(params, dk)
            c = run(params, jax.random.PRNGKey(8))
            ev, _ = jax.jit(lambda p: piped.apply(p, state, x))(params)

            def loss(p, k):
                logits, _ = piped.apply(p, state, x, train=True, rng=k)
                return softmax_cross_entropy(logits, y)

            g = jax.jit(jax.grad(loss))(params, dk)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert not np.allclose(np.asarray(a), np.asarray(c))
        assert not np.allclose(np.asarray(a), np.asarray(ev))
        assert all(np.isfinite(np.asarray(l)).all()
                   for l in jax.tree.leaves(g))


class TestMoEEngagement:
    """moe_ep_engaged surfacing + top_k validation (VERDICT r4 weak #6 /
    next #5; ADVICE r4)."""

    def _setup(self, n_experts=4):
        params = init_moe(jax.random.PRNGKey(0), dim=16, hidden=32,
                          n_experts=n_experts)
        x = jax.random.normal(jax.random.PRNGKey(1), (64, 16))
        return params, x

    def test_adaptive_engaged_on_matching_axis(self, ep_mesh):
        from dist_mnist_tpu.cluster.mesh import activate
        from dist_mnist_tpu.parallel.moe import moe_ffn_adaptive

        params, x = self._setup()
        with activate(ep_mesh):
            _, _, stats = jax.jit(moe_ffn_adaptive)(params, x)
        assert float(stats["ep_engaged"]) == 1.0

    def test_adaptive_dense_fallback_reports_zero(self, mesh_tp):
        """model axis 2 != 4 experts: dense fallback, and the stats SAY so
        — a jit-cached second call keeps saying so (the log warning
        doesn't)."""
        from dist_mnist_tpu.cluster.mesh import activate
        from dist_mnist_tpu.parallel.moe import moe_ffn_adaptive

        params, x = self._setup(n_experts=4)
        with activate(mesh_tp):  # model axis = 2
            fn = jax.jit(moe_ffn_adaptive)
            _, _, stats = fn(params, x)
            _, _, stats2 = fn(params, x)  # cached trace, same visibility
        assert float(stats["ep_engaged"]) == 0.0
        assert float(stats2["ep_engaged"]) == 0.0

    def test_adaptive_no_mesh_reports_zero(self):
        from dist_mnist_tpu.parallel.moe import moe_ffn_adaptive

        params, x = self._setup()
        _, _, stats = moe_ffn_adaptive(params, x)
        assert float(stats["ep_engaged"]) == 0.0

    def test_top_k_out_of_range_raises(self):
        from dist_mnist_tpu.parallel.moe import moe_ffn_dense

        params, x = self._setup(n_experts=4)
        with pytest.raises(ValueError, match="top_k"):
            moe_ffn_dense(params, x, top_k=5)
        with pytest.raises(ValueError, match="top_k"):
            moe_ffn_dense(params, x, top_k=0)


class TestPipelineSkipBubble:
    """skip_bubble wraps the stage in lax.cond(valid, fn, id): fill/drain
    ticks skip the compute, outputs must be IDENTICAL (garbage ticks only
    ever feed garbage ticks). VERDICT r4 weak #4 / next #6."""

    def _seq(self, stages, x):
        for p in stages:
            x = _stage_fn(p, x)
        return x

    @pytest.mark.parametrize("v", [1, 2])
    def test_matches_sequential_and_unskipped(self, pipe_mesh, v):
        dim, batch, n_stages = 16, 32, 4
        stages = _make_stages(jax.random.PRNGKey(20 + v), n_stages * v, dim)
        stacked = stack_stage_params(stages)
        x = jax.random.normal(jax.random.PRNGKey(21), (batch, dim))
        expected = self._seq(stages, x)
        kw = dict(num_microbatches=8, mesh=pipe_mesh, circular_chunks=v)
        got = pipeline_apply(_stage_fn, stacked, x, skip_bubble=True, **kw)
        base = pipeline_apply(_stage_fn, stacked, x, **kw)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                                   rtol=1e-5, atol=1e-5)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(base))

    def test_differentiable_with_rng(self, pipe_mesh):
        """cond + fori_loop + rng threading all compose under jax.grad."""
        dim, batch, n_stages = 8, 16, 4
        stages = _make_stages(jax.random.PRNGKey(22), n_stages, dim)
        stacked = stack_stage_params(stages)
        x = jax.random.normal(jax.random.PRNGKey(23), (batch, dim))
        base = jax.random.PRNGKey(24)

        def loss(sp, skip):
            y = pipeline_apply(_stage_fn_rng, sp, x, num_microbatches=4,
                               mesh=pipe_mesh, rng=base, skip_bubble=skip)
            return jnp.sum(y ** 2)

        g_skip = jax.grad(lambda sp: loss(sp, True))(stacked)
        g_base = jax.grad(lambda sp: loss(sp, False))(stacked)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
            g_skip, g_base,
        )
