"""ZeRO-style FSDP sharding: specs, trajectory parity, HBM reduction,
GSPMD collectives, resharding checkpoint restores, mesh-aware eval.

The acceptance contract of the `fsdp` strategy (ISSUE 3): on the 8-device
CPU mesh the loss trajectory matches `dp` within float tolerance, the
per-device param+opt-state bytes shrink >= 4x, GSPMD inserts the
param all-gather (visible in compiled HLO), and checkpoints round-trip
sharded->sharded AND across strategies (dp<->fsdp resharding restore).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dist_mnist_tpu import optim
from dist_mnist_tpu.data.pipeline import ShardedBatcher, batch_sharding, shard_batch
from dist_mnist_tpu.models import get_model
from dist_mnist_tpu.parallel.sharding import (
    DP_RULES,
    FSDP_RULES,
    derive_state_specs,
    shard_train_state,
)
from dist_mnist_tpu.train import create_train_state, evaluate, make_eval_step
from dist_mnist_tpu.train.state import state_memory_bytes
from dist_mnist_tpu.train.step import make_train_step


def _mlp_state(mesh, rules, hidden=64, optimizer=None):
    """MLP with FSDP-divisible dims (784 and 64 both divide 8) sharded
    under `rules`."""
    model = get_model("mlp", hidden_units=hidden)
    opt = optimizer or optim.adam(1e-3)
    state = create_train_state(model, opt, jax.random.PRNGKey(0),
                               jnp.zeros((1, 28, 28, 1), jnp.uint8))
    return model, opt, shard_train_state(state, mesh, rules)


def _params_equal(a, b) -> bool:
    return all(bool(jnp.allclose(x, y)) for x, y in
               zip(jax.tree.leaves(a.params), jax.tree.leaves(b.params)))


# ---------------------------------------------------------------- specs --


def test_opt_state_inherits_specs_through_chain_and_accumulation(mesh8):
    """Adam slots, chained-transform slots, and the accumulation buffer
    all mirror the param tree, so each leaf must inherit its param's
    FSDP spec — a regex over slot paths could never see the shapes the
    FSDP rule decides by."""
    model = get_model("mlp", hidden_units=64)
    opt = optim.gradient_accumulation(
        optim.chain(optim.clip_by_global_norm(1.0), optim.adam(1e-3)), 2
    )
    state = create_train_state(model, opt, jax.random.PRNGKey(0),
                               jnp.zeros((1, 28, 28, 1), jnp.uint8))
    specs = derive_state_specs(state, mesh8, FSDP_RULES)
    flat, _ = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: isinstance(x, P))
    by_path = {
        "/".join(str(getattr(k, "key", None) or getattr(k, "name", None)
                     or f"[{k.idx}]") for k in path): spec
        for path, spec in flat
    }
    hid_w = P("data", None)  # (784, 64): largest divisible dim is 784
    param_like = {p: s for p, s in by_path.items() if p.endswith("hid/w")}
    assert param_like, sorted(by_path)
    for path, spec in param_like.items():
        assert spec == hid_w, (path, spec)
    # counters never shard
    for path, spec in by_path.items():
        if path.endswith(("count", "calls")) or path in ("step", "rng"):
            assert spec == P(), (path, spec)


def test_put_via_callback_matches_device_put(mesh8):
    """The multi-process placement path (shard_train_state's no-broadcast
    alternative to device_put — the gloo `op.preamble.length <= op.nbytes`
    flake fix) must be bitwise-equal to device_put, leaf by leaf, with the
    same shardings — including the uint32 rng key and the scalar step."""
    from dist_mnist_tpu.parallel.sharding import (
        _put_via_callback,
        tree_sharding,
    )

    model = get_model("mlp", hidden_units=64)
    opt = optim.adam(1e-3)
    state = create_train_state(model, opt, jax.random.PRNGKey(0),
                               jnp.zeros((1, 28, 28, 1), jnp.uint8))
    shardings = tree_sharding(state, mesh8, FSDP_RULES)
    via_put = jax.device_put(state, shardings)
    via_cb = jax.tree.map(_put_via_callback, state, shardings)
    for a, b in zip(jax.tree.leaves(via_put), jax.tree.leaves(via_cb)):
        assert a.sharding == b.sharding
        assert np.asarray(a).tobytes() == np.asarray(b).tobytes()
        assert a.dtype == b.dtype


def test_shard_train_state_places_opt_state_sharded(mesh8):
    _, _, state = _mlp_state(mesh8, FSDP_RULES)
    assert state.params["hid"]["w"].sharding.spec == P("data", None)
    assert state.opt_state["m"]["hid"]["w"].sharding.spec == P("data", None)
    assert state.opt_state["v"]["sm"]["w"].sharding.spec == P("data", None)
    assert state.opt_state["count"].sharding.spec == P()
    assert state.rng.sharding.spec == P()


# ----------------------------------------------------- memory reduction --


def test_fsdp_shrinks_per_device_state_bytes_4x(mesh8):
    """The ZeRO claim, measured two ways: resident shard bytes (array
    shard_shape) and XLA's compiled argument bytes must BOTH shrink
    >= 4x vs dp on the 8-device mesh (lenet5: every big dim divides 8,
    so the actual factor is ~8x)."""
    model = get_model("lenet5")
    opt = optim.adam(1e-3)
    base = create_train_state(model, opt, jax.random.PRNGKey(0),
                              jnp.zeros((1, 28, 28, 1), jnp.uint8))
    img = np.zeros((64, 28, 28, 1), np.uint8)
    lab = np.zeros((64,), np.int32)
    measured = {}
    for name, rules in (("dp", DP_RULES), ("fsdp", FSDP_RULES)):
        state = shard_train_state(base, mesh8, rules)
        mem = state_memory_bytes(state)
        step = make_train_step(model, opt, mesh8, rules=rules, donate=False)
        batch = shard_batch({"image": img, "label": lab}, mesh8)
        ma = step.memory_analysis(state, batch)
        measured[name] = {
            "state": mem["param_bytes"] + mem["opt_state_bytes"],
            "args": getattr(ma, "argument_size_in_bytes", None),
        }
    assert measured["dp"]["state"] >= 4 * measured["fsdp"]["state"]
    if measured["dp"]["args"] and measured["fsdp"]["args"]:
        assert measured["dp"]["args"] >= 4 * measured["fsdp"]["args"]


# ------------------------------------------------------------ collectives --


def test_fsdp_compiled_step_all_gathers_params(mesh8):
    """GSPMD must implement the fsdp step as gather-on-use: the compiled
    HLO contains an all-gather under fsdp and none under dp (dp moves
    only grads, via all-reduce)."""
    img = np.zeros((64, 28, 28, 1), np.uint8)
    lab = np.zeros((64,), np.int32)
    texts = {}
    for name, rules in (("dp", DP_RULES), ("fsdp", FSDP_RULES)):
        model, opt, state = _mlp_state(mesh8, rules)
        step = make_train_step(model, opt, mesh8, rules=rules, donate=False)
        batch = shard_batch({"image": img, "label": lab}, mesh8)
        texts[name] = step.compiled_text(state, batch)
    if texts["dp"] is None or texts["fsdp"] is None:
        pytest.skip("backend cannot render compiled HLO text")
    assert "all-gather" in texts["fsdp"]
    assert "all-gather" not in texts["dp"]
    # grads still reduce in both ("all-reduce", or fused "reduce-scatter")
    assert ("all-reduce" in texts["fsdp"]) or ("reduce-scatter" in texts["fsdp"])
    assert "all-reduce" in texts["dp"]


# ------------------------------------------------------------- trajectory --


def test_fsdp_matches_dp_trajectory_two_epochs(mesh8, small_mnist):
    """Same seed, same batch stream, two full epochs: fsdp only changes
    WHERE bytes live, so the loss trajectory must match dp within float
    tolerance."""
    batch_size = 512
    steps_per_epoch = len(small_mnist.train_labels) // batch_size
    n_steps = 2 * steps_per_epoch
    assert n_steps >= 8
    traj = {}
    for name, rules in (("dp", DP_RULES), ("fsdp", FSDP_RULES)):
        model, opt, state = _mlp_state(mesh8, rules)
        step = make_train_step(model, opt, mesh8, rules=rules)
        batches = iter(ShardedBatcher(small_mnist, batch_size, mesh8, seed=0))
        losses = []
        for _ in range(n_steps):
            state, out = step(state, next(batches))
            losses.append(out["loss"])
        traj[name] = np.asarray(jax.device_get(losses), np.float64)
    np.testing.assert_allclose(traj["fsdp"], traj["dp"], rtol=1e-5, atol=1e-6)
    assert traj["dp"][-1] < traj["dp"][0]  # it actually trained


# ------------------------------------------------------------- checkpoint --


@pytest.mark.parametrize("src_name,dst_name", [
    ("fsdp", "fsdp"),  # sharded -> sharded
    ("dp", "fsdp"),    # resharding restore (the upgrade path)
    ("fsdp", "dp"),    # and back
])
def test_checkpoint_roundtrip_across_strategies(tmp_path, mesh8,
                                                src_name, dst_name):
    from dist_mnist_tpu.checkpoint import CheckpointManager

    rules = {"dp": DP_RULES, "fsdp": FSDP_RULES}
    model, opt, src = _mlp_state(mesh8, rules[src_name])
    src = dataclasses.replace(src, step=jnp.asarray(7, jnp.int32))
    mgr = CheckpointManager(tmp_path, async_save=False)
    try:
        assert mgr.save(src)
        mgr.wait()
        # a DIFFERENT init as the target proves values came from disk
        target = shard_train_state(
            create_train_state(model, opt, jax.random.PRNGKey(9),
                               jnp.zeros((1, 28, 28, 1), jnp.uint8)),
            mesh8, rules[dst_name])
        restored = mgr.restore(target)
    finally:
        mgr.close()
    assert restored.step_int == 7
    assert _params_equal(restored, src)
    # restored leaves carry the TARGET's (not the checkpoint's) shardings
    want = P("data", None) if dst_name == "fsdp" else P()
    assert restored.params["hid"]["w"].sharding.spec == want
    assert restored.opt_state["m"]["hid"]["w"].sharding.spec == want


# ------------------------------------------------------------------ eval --


def test_eval_step_derives_shardings_from_mesh_and_state(mesh8, small_mnist):
    """Satellite: make_eval_step must pin its in_shardings to the live
    state's placements + the mesh's batch sharding — a bare @jax.jit
    resharded an FSDP state to replicated for every eval batch."""
    model, opt, state = _mlp_state(mesh8, FSDP_RULES)
    eval_step = make_eval_step(model, mesh8)
    assert eval_step.captured_shardings() is None  # lazy until first call
    res = evaluate(eval_step, state, small_mnist.test_images,
                   small_mnist.test_labels, mesh8)
    state_shd, batch_shd = eval_step.captured_shardings()
    assert state_shd.params["hid"]["w"].spec == P("data", None)
    assert state_shd.opt_state["m"]["hid"]["w"].spec == P("data", None)
    assert batch_shd["image"] == batch_sharding(mesh8)
    assert batch_shd["label"] == batch_sharding(mesh8)
    # numerics: same state evaluated under dp placement agrees exactly
    model_dp, _, state_dp = _mlp_state(mesh8, DP_RULES)
    res_dp = evaluate(make_eval_step(model_dp, mesh8), state_dp,
                      small_mnist.test_images, small_mnist.test_labels, mesh8)
    assert res["n"] == res_dp["n"] == len(small_mnist.test_labels)
    np.testing.assert_allclose(res["loss"], res_dp["loss"], rtol=1e-6)
    np.testing.assert_allclose(res["accuracy"], res_dp["accuracy"], rtol=1e-6)


# ------------------------------------------------------------------ hook --


def test_memory_hook_reports_sharded_state(mesh8):
    from dist_mnist_tpu.hooks import MemoryHook

    class _Writer:
        def __init__(self):
            self.rows = []

        def scalars(self, vals, step):
            self.rows.append((dict(vals), step))

    class _Loop:
        initial_step = 0

    _, _, state = _mlp_state(mesh8, FSDP_RULES)
    _Loop.state = state
    writer = _Writer()
    hook = MemoryHook(writer, every_steps=10)
    hook.begin(_Loop())
    (vals, step), = writer.rows
    assert step == 0
    mem = state_memory_bytes(state)
    assert vals["memory/param_bytes_per_device"] == mem["param_bytes"]
    assert vals["memory/opt_state_bytes_per_device"] == mem["opt_state_bytes"]
    assert hook.last["memory/total_bytes_per_device"] == mem["total_bytes"]
