"""Metric writers (obs/writers.py) — scalar + histogram summary parity
(the reference wrote arbitrary summary protos, $TF
basic_session_run_hooks.py:793; scalars-only was VERDICT r2 missing item 6).
"""

import csv

import numpy as np

from dist_mnist_tpu.obs import (
    CsvWriter,
    MultiWriter,
    StdoutWriter,
    TensorBoardWriter,
    make_default_writer,
)


def _read_csv(path):
    with open(path) as fh:
        return list(csv.DictReader(fh))


def test_csv_scalar_and_histogram(tmp_path):
    w = CsvWriter(tmp_path / "m.csv")
    w.scalar("loss", 0.5, 1)
    w.histogram("weights", np.array([1.0, 2.0, 3.0, 4.0]), 2)
    w.flush()
    rows = _read_csv(tmp_path / "m.csv")
    assert {"step": "1", "tag": "loss", "value": "0.5"} in rows
    by_tag = {r["tag"]: r for r in rows if r["step"] == "2"}
    assert float(by_tag["weights/mean"]["value"]) == 2.5
    assert float(by_tag["weights/min"]["value"]) == 1.0
    assert float(by_tag["weights/max"]["value"]) == 4.0
    assert float(by_tag["weights/count"]["value"]) == 4


def test_stdout_histogram_logs(caplog):
    import logging

    with caplog.at_level(logging.INFO, logger="dist_mnist_tpu.obs.writers"):
        StdoutWriter().histogram("g", np.arange(8.0), 3)
    assert any("[hist] step=3 g:" in r.message for r in caplog.records)


def test_tensorboard_histogram_writes_events(tmp_path):
    w = TensorBoardWriter(tmp_path)
    if w._w is None:  # clu unavailable: degraded no-op path is the contract
        w.histogram("g", np.arange(8.0), 1)
        return
    w.scalar("loss", 1.0, 1)
    w.histogram("g", np.random.default_rng(0).normal(size=128), 1)
    w.flush()
    assert list(tmp_path.glob("events.out.tfevents.*"))


def test_multi_writer_fans_out(tmp_path):
    calls = []

    class Rec:
        def scalar(self, tag, value, step):
            calls.append(("s", tag))

        def histogram(self, tag, values, step):
            calls.append(("h", tag))

        def flush(self):
            calls.append(("f", None))

    m = MultiWriter(Rec(), Rec())
    m.scalar("a", 1.0, 0)
    m.histogram("b", np.zeros(3), 0)
    m.flush()
    assert calls == [("s", "a")] * 2 + [("h", "b")] * 2 + [("f", None)] * 2


def test_default_writer_non_chief_is_silent(tmp_path):
    w = make_default_writer(tmp_path, chief=False)
    w.scalar("x", 1.0, 0)
    w.histogram("y", np.zeros(2), 0)  # must not raise
    assert not list(tmp_path.iterdir())


def test_multi_writer_histogram_degrades_to_summary_scalars():
    """A scalar-only writer in the fan-out gets summary-stat scalars for a
    histogram write instead of crashing — same contract as `scalars`."""
    scalar_calls = []
    hist_calls = []

    class ScalarOnly:
        def scalar(self, tag, value, step):
            scalar_calls.append((tag, value, step))

        def flush(self):
            pass

    class Full:
        def scalar(self, tag, value, step):
            raise AssertionError("full writer must get the raw histogram")

        def histogram(self, tag, values, step):
            hist_calls.append((tag, step))

        def flush(self):
            pass

    m = MultiWriter(ScalarOnly(), Full())
    m.histogram("lat", np.array([1.0, 2.0, 3.0]), 7)
    assert hist_calls == [("lat", 7)]
    by_tag = {t: v for t, v, _ in scalar_calls}
    assert all(s == 7 for _, _, s in scalar_calls)
    assert set(by_tag) == {"lat/count", "lat/mean", "lat/std", "lat/min",
                           "lat/max"}
    assert by_tag["lat/count"] == 3.0
    assert by_tag["lat/mean"] == 2.0


def test_csv_writer_flush_cadence(tmp_path):
    """Rows become durable on disk every FLUSH_EVERY writes without an
    explicit flush() — bounds the window lost at abnormal exit."""
    path = tmp_path / "m.csv"
    w = CsvWriter(path)
    try:
        for i in range(CsvWriter.FLUSH_EVERY - 1):
            w.scalar("a", float(i), i)
        # still buffered (header was flushed-through by open; rows may sit
        # in the stdio buffer) — one more write crosses the threshold
        w.scalar("a", 99.0, 99)
        rows = _read_csv(path)  # read WITHOUT flush/close
        assert len(rows) == CsvWriter.FLUSH_EVERY
        assert w._unflushed == 0
        # batched writes count per-row toward the cadence, not per-call
        w.scalars({f"t{i}": float(i) for i in range(CsvWriter.FLUSH_EVERY)},
                  step=1)
        assert w._unflushed == 0
        assert len(_read_csv(path)) == 2 * CsvWriter.FLUSH_EVERY
    finally:
        w.close()


def test_csv_writer_close_flushes_and_is_idempotent(tmp_path):
    path = tmp_path / "m.csv"
    w = CsvWriter(path)
    w.scalar("loss", 0.25, 3)  # below cadence: only durable via close()
    w.close()
    assert _read_csv(path) == [{"step": "3", "tag": "loss", "value": "0.25"}]
    w.close()  # idempotent
    w.flush()  # post-close flush must not raise either


def test_multi_writer_close_propagates(tmp_path):
    """close() closes writers that support it and flushes the rest, so a
    CsvWriter in the fan-out releases its file handle."""
    calls = []

    class FlushOnly:
        def scalar(self, tag, value, step):
            pass

        def flush(self):
            calls.append("flush")

    csv_w = CsvWriter(tmp_path / "m.csv")
    m = MultiWriter(csv_w, FlushOnly())
    m.scalar("x", 1.0, 0)
    m.close()
    assert csv_w._fh.closed
    assert calls == ["flush"]
    assert _read_csv(tmp_path / "m.csv") == [
        {"step": "0", "tag": "x", "value": "1.0"}]
