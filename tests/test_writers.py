"""Metric writers (obs/writers.py) — scalar + histogram summary parity
(the reference wrote arbitrary summary protos, $TF
basic_session_run_hooks.py:793; scalars-only was VERDICT r2 missing item 6).
"""

import csv

import numpy as np

from dist_mnist_tpu.obs import (
    CsvWriter,
    MultiWriter,
    StdoutWriter,
    TensorBoardWriter,
    make_default_writer,
)


def _read_csv(path):
    with open(path) as fh:
        return list(csv.DictReader(fh))


def test_csv_scalar_and_histogram(tmp_path):
    w = CsvWriter(tmp_path / "m.csv")
    w.scalar("loss", 0.5, 1)
    w.histogram("weights", np.array([1.0, 2.0, 3.0, 4.0]), 2)
    w.flush()
    rows = _read_csv(tmp_path / "m.csv")
    assert {"step": "1", "tag": "loss", "value": "0.5"} in rows
    by_tag = {r["tag"]: r for r in rows if r["step"] == "2"}
    assert float(by_tag["weights/mean"]["value"]) == 2.5
    assert float(by_tag["weights/min"]["value"]) == 1.0
    assert float(by_tag["weights/max"]["value"]) == 4.0
    assert float(by_tag["weights/count"]["value"]) == 4


def test_stdout_histogram_logs(caplog):
    import logging

    with caplog.at_level(logging.INFO, logger="dist_mnist_tpu.obs.writers"):
        StdoutWriter().histogram("g", np.arange(8.0), 3)
    assert any("[hist] step=3 g:" in r.message for r in caplog.records)


def test_tensorboard_histogram_writes_events(tmp_path):
    w = TensorBoardWriter(tmp_path)
    if w._w is None:  # clu unavailable: degraded no-op path is the contract
        w.histogram("g", np.arange(8.0), 1)
        return
    w.scalar("loss", 1.0, 1)
    w.histogram("g", np.random.default_rng(0).normal(size=128), 1)
    w.flush()
    assert list(tmp_path.glob("events.out.tfevents.*"))


def test_multi_writer_fans_out(tmp_path):
    calls = []

    class Rec:
        def scalar(self, tag, value, step):
            calls.append(("s", tag))

        def histogram(self, tag, values, step):
            calls.append(("h", tag))

        def flush(self):
            calls.append(("f", None))

    m = MultiWriter(Rec(), Rec())
    m.scalar("a", 1.0, 0)
    m.histogram("b", np.zeros(3), 0)
    m.flush()
    assert calls == [("s", "a")] * 2 + [("h", "b")] * 2 + [("f", None)] * 2


def test_default_writer_non_chief_is_silent(tmp_path):
    w = make_default_writer(tmp_path, chief=False)
    w.scalar("x", 1.0, 0)
    w.histogram("y", np.zeros(2), 0)  # must not raise
    assert not list(tmp_path.iterdir())
