"""scripts/check_bench_regression.py: the tier-1 perf gate over
BENCH_*.json driver artifacts vs docs/PERF_ANCHOR.json."""

import json
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]

sys.path.insert(0, str(REPO / "scripts"))
try:
    from check_bench_regression import (
        DEFAULT_TOLERANCE,
        bench_records,
        check,
        main,
        newest_bench,
    )
finally:
    sys.path.pop(0)


def _bench(tmp_path, name="BENCH_r01.json", *, tail_recs=(), parsed=None):
    path = tmp_path / name
    doc = {"tail": "\n".join(json.dumps(r) for r in tail_recs)}
    if parsed is not None:
        doc["parsed"] = parsed
    path.write_text(json.dumps(doc))
    return path


def _anchor(tmp_path, entries):
    path = tmp_path / "PERF_ANCHOR.json"
    path.write_text(json.dumps({"_comment": "test", **entries}))
    return path


def test_regression_fails_below_floor(tmp_path):
    bench = _bench(tmp_path, tail_recs=[
        {"metric": "m1", "value": 80.0, "vs_anchor": 0.80}])
    anchor = _anchor(tmp_path, {"m1": {"value": 100.0}})
    ok, rows = check(bench, anchor)
    assert not ok
    assert rows[0]["status"] == "regression"
    assert rows[0]["floor"] == pytest.approx(1 - DEFAULT_TOLERANCE)
    assert main([f"--bench={bench}", f"--anchor={anchor}"]) == 1


def test_within_tolerance_and_per_metric_override(tmp_path):
    bench = _bench(tmp_path, tail_recs=[
        {"metric": "m1", "value": 95.0, "vs_anchor": 0.95},
        # 40% down but this metric declares a wider tolerance
        {"metric": "m2", "value": 6.0, "vs_anchor": 0.60},
    ])
    anchor = _anchor(tmp_path, {
        "m1": {"value": 100.0},
        "m2": {"value": 10.0, "tolerance": 0.5},
    })
    ok, rows = check(bench, anchor)
    assert ok
    assert {r["metric"]: r["status"] for r in rows} == {"m1": "ok",
                                                        "m2": "ok"}
    assert main([f"--bench={bench}", f"--anchor={anchor}"]) == 0


def test_improvement_never_fails(tmp_path):
    bench = _bench(tmp_path, tail_recs=[
        {"metric": "m1", "value": 200.0, "vs_anchor": 2.0}])
    anchor = _anchor(tmp_path, {"m1": {"value": 100.0}})
    ok, rows = check(bench, anchor)
    assert ok and rows[0]["status"] == "improved"


def test_clean_skips(tmp_path):
    """No artifact, no anchor, bench error, no vs_anchor: all exit 0."""
    anchor = _anchor(tmp_path, {"m1": {"value": 100.0}})
    # bench errored (backend down): vs_anchor absent, error present
    bench = _bench(tmp_path, parsed={
        "metric": "m1", "value": 0.0, "error": "backend probe failed"})
    ok, rows = check(bench, anchor)
    assert ok and rows[0]["status"] == "skip"
    # hardware mismatch: a record with no vs_anchor at all
    bench2 = _bench(tmp_path, "BENCH_r02.json",
                    tail_recs=[{"metric": "m1", "value": 50.0}])
    ok, rows = check(bench2, anchor)
    assert ok and rows[0]["status"] == "skip"
    # missing anchor file
    ok, rows = check(bench2, tmp_path / "absent.json")
    assert ok and rows[0]["status"] == "skip"
    # no bench artifact anywhere
    ok, rows = check(tmp_path / "absent_bench.json", anchor)
    assert ok and rows[0]["status"] == "skip"


def test_newest_bench_prefers_latest_round(tmp_path):
    for name in ("BENCH_r01.json", "BENCH_r03.json", "BENCH_r02.json"):
        (tmp_path / name).write_text("{}")
    assert newest_bench(tmp_path).name == "BENCH_r03.json"
    assert newest_bench(tmp_path / "empty") is None


def test_bench_records_merges_tail_and_parsed(tmp_path):
    bench = _bench(
        tmp_path,
        tail_recs=[{"metric": "m1", "vs_anchor": 0.5},
                   {"metric": "m1", "vs_anchor": 0.9},  # last wins
                   {"metric": "m2", "vs_anchor": 1.0},
                   {"not_a_metric": True}],
        parsed={"metric": "m3", "vs_anchor": 1.1},
    )
    recs = {r["metric"]: r for r in bench_records(bench)}
    assert set(recs) == {"m1", "m2", "m3"}
    assert recs["m1"]["vs_anchor"] == 0.9
    # malformed artifact: no records, never a crash
    bad = tmp_path / "BENCH_bad.json"
    bad.write_text("not json")
    assert bench_records(bad) == []


def test_real_repo_state_is_gateable():
    """The actual repo artifacts must pass the gate as-is (a regression
    here means either a real perf drop or a broken anchor file)."""
    ok, rows = check()
    assert ok, rows
