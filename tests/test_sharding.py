"""Mesh construction + placement rules (replica_device_setter analogue)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dist_mnist_tpu.cluster.mesh import MeshSpec, local_batch_slice, make_mesh
from dist_mnist_tpu.parallel.sharding import (
    DP_RULES,
    TP_RULES,
    ShardingRules,
    tree_sharding,
)


def test_mesh_spec_resolution():
    assert MeshSpec(data=-1).resolve(8) == (8, 1, 1, 1)
    assert MeshSpec(data=-1, model=2).resolve(8) == (4, 2, 1, 1)
    assert MeshSpec(data=2, model=2, seq=2).resolve(8) == (2, 2, 2, 1)
    assert MeshSpec(data=-1, pipe=4).resolve(8) == (2, 1, 1, 4)
    with pytest.raises(ValueError):
        MeshSpec(data=3).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(data=-1, model=3).resolve(8)


def test_make_mesh_axes():
    mesh = make_mesh(MeshSpec(data=4, model=2))
    assert mesh.shape == {"data": 4, "model": 2, "seq": 1, "pipe": 1}
    assert len(set(d.id for d in mesh.devices.flat)) == 8


def test_local_batch_slice(mesh8):
    per_proc, per_dev = local_batch_slice(64, mesh8)
    assert per_proc == 64  # single process
    assert per_dev == 8
    with pytest.raises(ValueError):
        local_batch_slice(65, mesh8)


def test_dp_rules_replicate_everything(mesh8):
    tree = {"layer": {"w": jnp.zeros((4, 4)), "b": jnp.zeros((4,))}}
    shardings = tree_sharding(tree, mesh8, DP_RULES)
    assert shardings["layer"]["w"].spec == P()
    assert shardings["layer"]["b"].spec == P()


def test_tp_rules_megatron_pattern(mesh_tp):
    tree = {
        "block0": {
            "attn": {
                "qkv": {"w": jnp.zeros((8, 24)), "b": jnp.zeros((24,))},
                "out": {"w": jnp.zeros((8, 8)), "b": jnp.zeros((8,))},
            },
            "mlp_in": {"w": jnp.zeros((8, 32)), "b": jnp.zeros((32,))},
            "mlp_out": {"w": jnp.zeros((32, 8)), "b": jnp.zeros((8,))},
        }
    }
    s = tree_sharding(tree, mesh_tp, TP_RULES)
    blk = s["block0"]
    # column-parallel: output dim sharded
    assert blk["attn"]["qkv"]["w"].spec == P(None, "model")
    assert blk["attn"]["qkv"]["b"].spec == P("model")
    assert blk["mlp_in"]["w"].spec == P(None, "model")
    # row-parallel: input dim sharded, bias replicated
    assert blk["attn"]["out"]["w"].spec == P("model", None)
    assert blk["attn"]["out"]["b"].spec == P()
    assert blk["mlp_out"]["w"].spec == P("model", None)
    assert blk["mlp_out"]["b"].spec == P()


def test_nonmatching_rules_refused(mesh_tp):
    """TP rules over a model with no TP-shaped params must raise, not
    silently train replicated under the strategy's name (VERDICT r3 weak 6)."""
    from dist_mnist_tpu import optim
    from dist_mnist_tpu.parallel.sharding import shard_train_state
    from dist_mnist_tpu.train import create_train_state
    from dist_mnist_tpu.models import get_model

    model = get_model("lenet5")  # conv params: no qkv/mlp_in/fc paths TP matches
    state = create_train_state(
        model, optim.adam(0.01), jax.random.PRNGKey(0),
        jnp.zeros((1, 28, 28, 1), jnp.uint8),
    )
    if TP_RULES.match_count(state.params) == 0:
        with pytest.raises(ValueError, match="matched no parameter"):
            shard_train_state(state, mesh_tp, TP_RULES)
    else:  # if lenet ever grows a matching path, the guard must stay quiet
        shard_train_state(state, mesh_tp, TP_RULES)
    # DP (empty rules) always passes
    shard_train_state(state, mesh_tp, DP_RULES)


def test_named_strategy_matching_nothing_always_raises(mesh8):
    """Deterministic companion to test_nonmatching_rules_refused: that test
    only exercises the refusal branch IF lenet5 happens not to match TP —
    this one pins the contract unconditionally, for both rule kinds."""
    from dist_mnist_tpu.parallel.sharding import FSDP_RULES, shard_train_state
    from dist_mnist_tpu.train.state import TrainState

    # (3, 5) floats: no dim divides the 8-way data axis, and no regex below
    # matches the path — both named strategies resolve to zero matches.
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params={"conv": {"w": jnp.zeros((3, 5))}},
        model_state={},
        opt_state={},
        rng=jax.random.PRNGKey(0),
    )
    named_regex = ShardingRules(rules=((r"qkv/w$", (None, "model")),))
    assert named_regex.match_count(state.params, mesh8) == 0
    with pytest.raises(ValueError, match="matched no parameter"):
        shard_train_state(state, mesh8, named_regex)

    assert FSDP_RULES.match_count(state.params, mesh8) == 0
    with pytest.raises(ValueError, match="matched no parameter"):
        shard_train_state(state, mesh8, FSDP_RULES)


def test_fsdp_rule_picks_largest_divisible_free_dim(mesh8):
    from dist_mnist_tpu.parallel.sharding import FSDP_RULES

    # (16, 128): both divide 8; the LARGER dim (128) takes the data axis
    assert FSDP_RULES.leaf_spec("w", jnp.zeros((16, 128)), mesh8) == P(None, "data")
    assert FSDP_RULES.leaf_spec("w2", jnp.zeros((128, 16)), mesh8) == P("data", None)
    assert FSDP_RULES.leaf_spec("b", jnp.zeros((8,)), mesh8) == P("data")
    # integer leaves and non-divisible shapes stay replicated
    assert FSDP_RULES.leaf_spec("c", jnp.zeros((8,), jnp.int32), mesh8) == P()
    assert FSDP_RULES.leaf_spec("d", jnp.zeros((3, 5)), mesh8) == P()
    assert FSDP_RULES.leaf_spec("s", jnp.zeros(()), mesh8) == P()


def test_fsdp_composes_with_tp(mesh_tp):
    """fsdp_tp: TP's regex owns the `model` placement; FSDP adds `data`
    (size 4 here) on the largest remaining free divisible dim."""
    from dist_mnist_tpu.parallel.sharding import FSDP_TP_RULES

    # column-parallel qkv/w (8, 24): TP -> P(None, "model"); dim0=8 %4==0
    assert (FSDP_TP_RULES.leaf_spec("blk/attn/qkv/w", jnp.zeros((8, 24)), mesh_tp)
            == P("data", "model"))
    # row-parallel out/w (24, 8): TP -> P("model", None); dim1=8 %4==0
    assert (FSDP_TP_RULES.leaf_spec("blk/attn/out/w", jnp.zeros((24, 8)), mesh_tp)
            == P("model", "data"))
    # TP-untouched param falls through to the pure FSDP shape rule
    assert (FSDP_TP_RULES.leaf_spec("embed/w", jnp.zeros((12, 16)), mesh_tp)
            == P(None, "data"))
    # TP match whose free dim is not divisible: keep the TP spec as-is
    assert (FSDP_TP_RULES.leaf_spec("blk/attn/qkv/w", jnp.zeros((7, 24)), mesh_tp)
            == P(None, "model"))


def test_custom_rule_ordering():
    rules = ShardingRules(rules=(
        (r"special/w$", ("data",)),
        (r"w$", ("model",)),
    ))
    assert rules.spec_for("special/w", 1) == P("data")
    assert rules.spec_for("other/w", 1) == P("model")
    assert rules.spec_for("other/b", 1) == P()


def test_opt_state_inherits_param_specs(mesh_tp):
    """Adam m/v mirror params structurally, so the same path rules colocate
    slot shards with param shards (PS slot-colocation analogue)."""
    from dist_mnist_tpu import optim

    params = {"mlp_in": {"w": jnp.zeros((8, 32)), "b": jnp.zeros((32,))}}
    opt_state = optim.adam(0.01).init(params)
    s = tree_sharding({"opt": opt_state}, mesh_tp, TP_RULES)
    assert s["opt"]["m"]["mlp_in"]["w"].spec == P(None, "model")
    assert s["opt"]["v"]["mlp_in"]["w"].spec == P(None, "model")
    assert s["opt"]["count"].spec == P()


def test_hybrid_mesh_shapes():
    """Multislice factoring: DCN factor rides the data axis only."""
    from dist_mnist_tpu.cluster.mesh import hybrid_mesh_shapes, slice_count

    ici, dcn = hybrid_mesh_shapes((8, 2, 1, 1), num_slices=2)
    assert ici == (4, 2, 1, 1)
    assert dcn == (2, 1, 1, 1)
    # elementwise product reassembles the logical shape
    assert tuple(a * b for a, b in zip(ici, dcn)) == (8, 2, 1, 1)

    # data axis can't absorb the slices -> pipe (also DCN-tolerant) takes it
    ici, dcn = hybrid_mesh_shapes((1, 2, 1, 4), num_slices=2)
    assert ici == (1, 2, 1, 2)
    assert dcn == (1, 1, 1, 2)

    # slice factor split across BOTH DCN-tolerant axes: 4 = 2(data) x 2(pipe)
    ici, dcn = hybrid_mesh_shapes((2, 3, 1, 2), num_slices=4)
    assert ici == (1, 3, 1, 1)
    assert dcn == (2, 1, 1, 2)

    # neither data nor pipe divisible -> None (caller warns + plain layout)
    assert hybrid_mesh_shapes((6, 1, 1, 1), num_slices=4) is None

    class _Dev:
        def __init__(self, slice_index=None):
            self.slice_index = slice_index

    assert slice_count([_Dev(0), _Dev(0), _Dev(1)]) == 2
    assert slice_count([_Dev(None), _Dev(None)]) == 1
