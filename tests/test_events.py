"""Run journal (obs/events.py): record shape, ordering, the shared-file
multi-generation contract, the module-level current-journal seam, and the
scripts/tail_run.py renderer."""

import json
import os
import subprocess
import sys
import threading
from pathlib import Path

import pytest

from dist_mnist_tpu.obs import events
from dist_mnist_tpu.obs.events import (
    RunJournal,
    read_journal,
    tail_journal,
)

REPO = Path(__file__).resolve().parent.parent


@pytest.fixture(autouse=True)
def _no_ambient_journal():
    """These tests install/remove the process-wide journal; never let one
    leak into (or in from) another test."""
    prev = events.set_journal(None)
    yield
    events.set_journal(prev)


def test_record_shape_and_seq(tmp_path):
    path = tmp_path / "j.jsonl"
    with RunJournal(path, generation=3) as j:
        j.emit("run_start", config="mlp_mnist")
        j.emit("checkpoint_save", step=10)
    recs = read_journal(path)
    assert [r["event"] for r in recs] == ["run_start", "checkpoint_save"]
    assert [r["seq"] for r in recs] == [0, 1]
    for r in recs:
        assert r["pid"] == os.getpid()
        assert r["gen"] == 3
        assert isinstance(r["ts"], float)
    assert recs[0]["config"] == "mlp_mnist"
    assert recs[1]["step"] == 10


def test_records_are_single_compact_lines(tmp_path):
    path = tmp_path / "j.jsonl"
    with RunJournal(path) as j:
        j.emit("x", nested={"a": 1}, obj=object())  # default=str coverage
    lines = path.read_text().splitlines()
    assert len(lines) == 1
    assert json.loads(lines[0])["nested"] == {"a": 1}


def test_shared_file_across_generations(tmp_path):
    """The supervisor contract: one file, many writers over time — each
    generation appends, nothing is truncated."""
    path = tmp_path / "j.jsonl"
    for gen in range(3):
        with RunJournal(path, generation=gen) as j:
            j.emit("run_start")
            j.emit("run_stop")
    recs = read_journal(path)
    assert [r["gen"] for r in recs] == [0, 0, 1, 1, 2, 2]
    # seq restarts per journal instance; (gen, seq) orders the whole file
    assert [r["seq"] for r in recs] == [0, 1] * 3


def test_gen_field_override(tmp_path):
    """Supervisor records carry the generation as an explicit field (one
    journal instance spans all attempts)."""
    path = tmp_path / "j.jsonl"
    with RunJournal(path) as j:
        j.emit("generation_start", gen=2)
    assert read_journal(path)[0]["gen"] == 2


def test_emit_without_journal_is_noop():
    events.emit("nobody_listening", x=1)  # must not raise
    assert events.get_journal() is None


def test_set_journal_returns_previous(tmp_path):
    a = RunJournal(tmp_path / "a.jsonl")
    b = RunJournal(tmp_path / "b.jsonl")
    try:
        assert events.set_journal(a) is None
        assert events.set_journal(b) is a
        events.emit("hello")
        assert events.set_journal(None) is b
        assert [r["event"] for r in read_journal(tmp_path / "b.jsonl")] == [
            "hello"]
        assert read_journal(tmp_path / "a.jsonl") == []
    finally:
        a.close()
        b.close()


def test_emit_after_close_is_safe(tmp_path):
    j = RunJournal(tmp_path / "j.jsonl")
    j.close()
    j.close()  # idempotent
    events.set_journal(j)
    events.emit("late")  # swallowed, never raises
    assert read_journal(tmp_path / "j.jsonl") == []


def test_read_journal_skips_malformed(tmp_path):
    path = tmp_path / "j.jsonl"
    with RunJournal(path) as j:
        j.emit("good")
    with open(path, "a") as fh:
        fh.write("{torn line\n")
    with RunJournal(path) as j:
        j.emit("also_good")
    assert [r["event"] for r in read_journal(path)] == ["good", "also_good"]


def test_read_missing_file():
    assert read_journal("/no/such/journal.jsonl") == []


def test_tail_journal(tmp_path):
    path = tmp_path / "j.jsonl"
    with RunJournal(path) as j:
        for i in range(10):
            j.emit("e", i=i)
    assert [r["i"] for r in tail_journal(path, 3)] == [7, 8, 9]
    assert len(tail_journal(path, 0)) == 10  # 0 = everything
    assert len(tail_journal(path, -1)) == 10


def test_concurrent_emits_no_torn_lines(tmp_path):
    path = tmp_path / "j.jsonl"
    with RunJournal(path) as j:
        threads = [
            threading.Thread(
                target=lambda k=k: [j.emit("t", worker=k, n=i)
                                    for i in range(200)])
            for k in range(4)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    recs = read_journal(path)
    assert len(recs) == 800
    assert sorted(r["seq"] for r in recs) == list(range(800))


def test_tail_run_script(tmp_path):
    path = tmp_path / "j.jsonl"
    with RunJournal(path, generation=1) as j:
        j.emit("run_start", config="mlp_mnist")
        j.emit("preemption", step=40)
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "tail_run.py"), str(path)],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    lines = out.stdout.strip().splitlines()
    assert len(lines) == 2
    assert "run_start" in lines[0] and "config=mlp_mnist" in lines[0]
    assert "preemption" in lines[1] and "step=40" in lines[1]
    assert "g1" in lines[0]


def test_tail_run_script_missing_file(tmp_path):
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "tail_run.py"),
         str(tmp_path / "absent.jsonl")],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode == 1
    assert "tail_run" in out.stderr
