"""Tier-1 serve-subsystem tests: batcher coalescing, compiled-model cache,
admission control, deadlines, drain, checkpoint loading. All CPU-mesh, no
sockets, no sleeps longer than the coalesce windows under test."""

from __future__ import annotations

import time

import numpy as np
import pytest

from dist_mnist_tpu.serve import (
    AdmissionQueue,
    DeadlineExceededError,
    InferenceEngine,
    InferenceServer,
    QueueFullError,
    ServeConfig,
    ServeMetrics,
    ShuttingDownError,
    load_for_serving,
    run_loadgen,
)

IMAGE_SHAPE = (28, 28, 1)


@pytest.fixture(scope="module")
def bundle(mesh8):
    return load_for_serving("mlp_mnist", mesh8)


@pytest.fixture(scope="module")
def engine(mesh8, bundle):
    return InferenceEngine(
        bundle.model, bundle.params, bundle.model_state, mesh8,
        model_name="mlp", image_shape=bundle.image_shape,
        rules=bundle.rules, max_bucket=64,
    )


def _images(n, seed=0):
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, size=(n, *IMAGE_SHAPE), dtype=np.uint8)


# -- engine: bucketing + compiled cache --------------------------------------

def test_bucketing_pow2_and_data_axis_floor(engine):
    # data axis is 8 -> min bucket 8; everything a power of two, capped
    assert engine.min_bucket == 8
    assert [engine.bucket_for(n) for n in (1, 7, 8, 9, 16, 33, 64)] == \
        [8, 8, 8, 16, 16, 64, 64]
    assert engine.buckets() == [8, 16, 32, 64]
    with pytest.raises(ValueError, match="max_bucket"):
        engine.bucket_for(65)


def test_cache_hits_and_misses(engine):
    base = engine.cache.stats()
    out = engine.predict(_images(3))
    assert out.shape == (3, 10)
    mid = engine.cache.stats()
    assert mid["misses"] == base["misses"] + 1
    # same bucket (5 -> 8, like 3 -> 8): must NOT recompile
    engine.predict(_images(5, seed=1))
    after = engine.cache.stats()
    assert after["misses"] == mid["misses"]
    assert after["hits"] == mid["hits"] + 1
    # a new bucket is a miss again
    engine.predict(_images(9, seed=2))
    assert engine.cache.stats()["misses"] == mid["misses"] + 1
    # compile/execute attribution was recorded (utils/timing.stopclock)
    assert engine.cache.stats()["compile_secs"] > 0
    assert engine.cache.stats()["execute_secs"] > 0


def test_padding_rows_do_not_change_real_logits(engine):
    x = _images(3, seed=3)
    # n=3 pads to bucket 8; each row alone pads to 8 too — rows must agree
    batched = engine.predict(x)
    single = np.stack([engine.predict(x[i:i + 1])[0] for i in range(3)])
    np.testing.assert_allclose(batched, single, atol=1e-5)


def test_prewarm_compiles_all_buckets(mesh8, bundle):
    eng = InferenceEngine(
        bundle.model, bundle.params, bundle.model_state, mesh8,
        model_name="mlp-prewarm", image_shape=bundle.image_shape,
        rules=bundle.rules, max_bucket=16,
    )
    n = eng.prewarm()
    assert n == len(eng.buckets()) == 2
    # live traffic after prewarm never compiles
    eng.predict(_images(4))
    eng.predict(_images(12))
    s = eng.cache.stats()
    assert s["misses"] == n and s["hits"] == 2


# -- admission control --------------------------------------------------------

def test_queue_full_rejection_is_bounded_and_counted():
    m = ServeMetrics()
    q = AdmissionQueue(depth=4, metrics=m)
    futs = [q.submit(np.zeros(IMAGE_SHAPE, np.uint8)) for _ in range(4)]
    with pytest.raises(QueueFullError):
        q.submit(np.zeros(IMAGE_SHAPE, np.uint8))
    assert m.snapshot()["rejected_queue_full"] == 1
    assert m.snapshot()["admitted"] == 4
    assert q.depth == 4 and len(futs) == 4


def test_closed_queue_rejects_with_shutdown():
    m = ServeMetrics()
    q = AdmissionQueue(depth=4, metrics=m)
    q.close()
    with pytest.raises(ShuttingDownError):
        q.submit(np.zeros(IMAGE_SHAPE, np.uint8))
    assert m.snapshot()["rejected_shutdown"] == 1


# -- server integration -------------------------------------------------------

def test_coalescing_under_64_concurrent_requests(engine):
    """The acceptance path: >=64 concurrent in-flight requests on the
    8-device CPU mesh must coalesce (mean executed batch > 1, visible via
    the batch-occupancy metric), with the compiled cache serving repeat
    buckets and p50/p99 reported."""
    server = InferenceServer(engine, ServeConfig(
        max_batch=32, max_wait_ms=20.0, queue_depth=256, prewarm=False,
    ))
    engine.prewarm()  # buckets may already be warm from earlier tests
    with server:
        summary = run_loadgen(
            server, n_requests=256, concurrency=64,
            image_shape=IMAGE_SHAPE, seed=0,
        )
    assert summary["ok"] == 256
    assert summary["errors"] == 0
    assert summary["mean_batch_size"] > 1.0, summary
    assert summary["n_batches"] < 256  # genuinely coalesced
    assert np.isfinite(summary["p50_ms"]) and np.isfinite(summary["p99_ms"])
    assert summary["p50_ms"] <= summary["p99_ms"]
    assert summary["cache"]["hits"] > 0  # repeat buckets did not recompile
    # occupancy reservoir was populated (0 < occupancy <= 1)
    assert 0.0 < summary["mean_occupancy"] <= 1.0


def test_results_are_correct_through_the_batcher(engine, bundle):
    """Coalesced answers equal direct engine answers row-for-row."""
    x = _images(10, seed=7)
    direct = engine.predict(x)
    server = InferenceServer(engine, ServeConfig(
        max_batch=16, max_wait_ms=10.0, queue_depth=64, prewarm=False,
    ))
    with server:
        futs = [server.submit(x[i]) for i in range(10)]
        results = [f.result(timeout=30) for f in futs]
    for i, res in enumerate(results):
        np.testing.assert_allclose(res.logits, direct[i], atol=1e-5)
        assert res.label == int(direct[i].argmax())
        assert res.latency_ms >= 0


def test_overload_rejects_but_serves_admitted(engine):
    """With a tiny queue and a slowed engine, a burst must produce bounded
    rejections — and every ADMITTED request still completes."""
    server = InferenceServer(engine, ServeConfig(
        max_batch=8, max_wait_ms=1.0, queue_depth=8, prewarm=False,
    ))
    orig_predict = engine.predict
    slow = lambda images: (time.sleep(0.05), orig_predict(images))[1]
    engine.predict = slow
    try:
        with server:
            futs, rejected = [], 0
            for i in range(64):
                try:
                    futs.append(server.submit(_images(1)[0]))
                except QueueFullError:
                    rejected += 1
            done = [f.result(timeout=30) for f in futs]
    finally:
        engine.predict = orig_predict
    assert rejected > 0
    assert len(done) == 64 - rejected
    assert server.stats()["rejected_queue_full"] == rejected


def test_deadline_expiry_in_queue(engine):
    """A request whose deadline passes while queued gets
    DeadlineExceededError, not a stale answer."""
    server = InferenceServer(engine, ServeConfig(
        max_batch=8, max_wait_ms=1.0, queue_depth=64, prewarm=False,
    ))
    orig_predict = engine.predict
    engine.predict = lambda images: (time.sleep(0.08), orig_predict(images))[1]
    try:
        with server:
            # first request occupies the engine; the second expires in queue
            f1 = server.submit(_images(1)[0])
            time.sleep(0.02)  # let the batcher take f1 into its window
            f2 = server.submit(_images(1)[0], deadline_ms=1.0)
            f1.result(timeout=30)
            with pytest.raises(DeadlineExceededError):
                f2.result(timeout=30)
    finally:
        engine.predict = orig_predict
    assert server.stats()["rejected_deadline"] >= 1


def test_drain_finishes_inflight_then_rejects_new(engine):
    server = InferenceServer(engine, ServeConfig(
        max_batch=8, max_wait_ms=5.0, queue_depth=128, prewarm=False,
    ))
    server.start()
    x = _images(32, seed=11)
    futs = [server.submit(x[i]) for i in range(32)]
    assert server.close(timeout=60) is True  # drains, doesn't drop
    for f in futs:
        assert f.result(timeout=1).logits.shape == (10,)
    with pytest.raises(ShuttingDownError):
        server.submit(x[0])
    snap = server.stats()
    assert snap["completed"] == 32
    assert snap["rejected_shutdown"] == 1


def test_engine_failure_fails_batch_not_server(engine):
    server = InferenceServer(engine, ServeConfig(
        max_batch=8, max_wait_ms=1.0, queue_depth=64, prewarm=False,
    ))
    orig_predict = engine.predict
    calls = []

    def flaky(images):
        if not calls:
            calls.append(1)
            raise RuntimeError("injected")
        return orig_predict(images)

    engine.predict = flaky
    try:
        with server:
            f1 = server.submit(_images(1)[0])
            with pytest.raises(RuntimeError, match="injected"):
                f1.result(timeout=30)
            # server survived: next request is served normally
            f2 = server.submit(_images(1, seed=1)[0])
            assert f2.result(timeout=30).logits.shape == (10,)
    finally:
        engine.predict = orig_predict
    assert server.stats()["failed"] == 1


# -- metrics writer integration ----------------------------------------------

def test_metrics_emit_through_obs_writer(engine):
    rows = []

    class Capture:
        def scalar(self, tag, value, step):
            rows.append(("scalar", tag))

        def histogram(self, tag, values, step):
            rows.append(("hist", tag))

        def flush(self):
            rows.append(("flush", ""))

    server = InferenceServer(engine, ServeConfig(
        max_batch=8, max_wait_ms=5.0, queue_depth=64, prewarm=False,
    ), writer=Capture())
    with server:
        fut = server.submit(_images(1)[0])
        fut.result(timeout=30)
    tags = {t for _, t in rows}
    assert "serve/latency_p99_ms" in tags
    assert "serve/batch_occupancy" in tags
    assert "serve/queue_depth" in tags
    assert "serve/cache_hits" in tags
    assert ("flush", "") in rows


# -- loader -------------------------------------------------------------------

def test_loader_restores_weights_without_optimizer(mesh8, tmp_path):
    import dataclasses

    import jax
    import jax.numpy as jnp

    from dist_mnist_tpu.checkpoint.manager import CheckpointManager
    from dist_mnist_tpu.configs import get_config
    from dist_mnist_tpu.models.registry import get_model
    from dist_mnist_tpu.optim import adam
    from dist_mnist_tpu.train.state import create_train_state

    cfg = get_config("mlp_mnist")
    model = get_model(cfg.model, **cfg.model_kwargs)
    sample = jnp.zeros((1, *IMAGE_SHAPE), jnp.float32)
    state = create_train_state(model, adam(1e-3),
                               jax.random.PRNGKey(cfg.seed), sample)
    # make the weights distinguishable from a fresh init
    state = dataclasses.replace(
        state,
        step=jnp.asarray(42, jnp.int32),
        params=jax.tree.map(lambda p: p + 1.0, state.params),
    )
    mgr = CheckpointManager(tmp_path / "ckpt", async_save=False)
    assert mgr.save(state)
    mgr.wait()
    mgr.close()

    bundle = load_for_serving(cfg, mesh8, checkpoint_dir=tmp_path / "ckpt")
    assert bundle.restored and bundle.step == 42
    for a, b in zip(jax.tree.leaves(bundle.params),
                    jax.tree.leaves(state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_loader_fresh_init_without_checkpoint(mesh8, bundle):
    assert not bundle.restored and bundle.step == 0
    assert bundle.image_shape == IMAGE_SHAPE
    assert bundle.num_classes == 10
