"""Chrome-trace export (obs/timeline.py — client/timeline.py analogue)."""

import gzip
import json

import jax
import jax.numpy as jnp
import pytest

from dist_mnist_tpu.obs import export_chrome_trace, latest_trace, summarize_trace


@pytest.fixture(scope="module")
def profile_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("prof")
    with jax.profiler.trace(str(d)):
        x = jnp.ones((256, 256))
        jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
    return d


def test_latest_trace_found(profile_dir):
    assert latest_trace(profile_dir) is not None


def test_export_chrome_trace(profile_dir, tmp_path):
    out = export_chrome_trace(profile_dir)
    assert out is not None and out.name.startswith("timeline-")
    data = json.loads(out.read_text())
    assert "traceEvents" in data and len(data["traceEvents"]) > 0


def test_export_no_trace_returns_none(tmp_path):
    assert export_chrome_trace(tmp_path) is None
    assert latest_trace(tmp_path) is None


def test_summarize_trace(profile_dir):
    rows = summarize_trace(latest_trace(profile_dir))
    assert rows, "profiler produced no complete events"
    assert rows == sorted(rows, key=lambda r: -r["total_us"])
    for r in rows:
        # total is rounded to 1 dp, avg to 2 dp — allow the rounding gap
        assert r["count"] >= 1 and r["avg_us"] <= r["total_us"] + 0.06


def test_profiler_hook_end_exports(tmp_path):
    """A run shorter than the trace window still gets the chrome-trace
    export on end() — same path as the cadence stop (ADVICE r1 item 1)."""
    from dist_mnist_tpu.hooks.builtin import ProfilerHook

    class FakeLoop:
        initial_step = 0

    hook = ProfilerHook(str(tmp_path), start_step=0, num_steps=100)
    hook.begin(FakeLoop())
    hook.before_step(0)  # trace window opens
    x = jnp.ones((128, 128))
    jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
    hook.after_step(1, None, {"loss": x[0, 0]})  # window unfinished
    hook.end(None)  # early end: must stop the trace AND export
    assert latest_trace(tmp_path) is not None
    assert list(tmp_path.rglob("timeline-*.json"))


def test_profiler_hook_chunked_loop_still_traces(tmp_path):
    """A chunked loop strides past the exact start step; the hook must
    still capture a window (and not restart after it completed)."""
    from dist_mnist_tpu.hooks.builtin import ProfilerHook

    class FakeLoop:
        initial_step = 0

    hook = ProfilerHook(str(tmp_path), start_step=10, num_steps=3)
    hook.begin(FakeLoop())
    hook.before_step(0)
    assert not hook._active  # window not reached yet
    hook.before_step(100)  # strides past start=10 -> trace opens
    assert hook._active
    x = jnp.ones((64, 64))
    jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
    hook.after_step(200, None, {"loss": x[0, 0]})  # past stop -> closes
    assert not hook._active
    hook.before_step(300)  # completed window must NOT restart
    assert not hook._active
    assert latest_trace(tmp_path) is not None


def test_profiler_hook_single_chunk_run_traces(tmp_path):
    """When the whole run is ONE scan chunk, the window start aligns down
    to the chunk boundary so the (only) chunk is the one traced."""
    from dist_mnist_tpu.hooks.builtin import ProfilerHook

    class ChunkedLoop:
        initial_step = 0
        steps_per_call = 200

    hook = ProfilerHook(str(tmp_path), start_step=10, num_steps=3)
    hook.begin(ChunkedLoop())
    hook.before_step(0)
    assert hook._active  # window aligned to chunk boundary 0
    x = jnp.ones((64, 64))
    jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
    hook.after_step(200, None, {"loss": x[0, 0]})
    assert not hook._active
    assert latest_trace(tmp_path) is not None


def test_summarize_synthetic_trace(tmp_path):
    """Deterministic check of aggregation math on a hand-written trace."""
    trace = {
        "traceEvents": [
            {"ph": "X", "name": "matmul", "dur": 10.0},
            {"ph": "X", "name": "matmul", "dur": 30.0, "pid": 7, "tid": 2},
            {"ph": "X", "name": "relu", "dur": 5.0},
            {"ph": "M", "name": "meta-only"},
        ]
    }
    p = tmp_path / "t.trace.json.gz"
    p.write_bytes(gzip.compress(json.dumps(trace).encode()))
    rows = summarize_trace(p)
    # events missing pid/tid aggregate under the (0, 0) default track
    assert rows[0] == {"name": "matmul", "total_us": 40.0, "count": 2,
                       "avg_us": 20.0, "tracks": 2}
    assert rows[1]["name"] == "relu" and rows[1]["tracks"] == 1


def test_summarize_trace_without_trace_events(tmp_path):
    """A trace with no `traceEvents` key (or an empty list) summarizes to
    no rows — not a KeyError mid-triage."""
    p1 = tmp_path / "empty.json"
    p1.write_text("{}")
    assert summarize_trace(p1) == []
    p2 = tmp_path / "no_complete.json"
    p2.write_text(json.dumps({"traceEvents": []}))
    assert summarize_trace(p2) == []
    # metadata-only events (no ph=X / no dur) likewise aggregate to nothing
    p3 = tmp_path / "meta.json"
    p3.write_text(json.dumps({"traceEvents": [
        {"ph": "M", "name": "process_name"},
        {"ph": "X", "name": "no-dur"},
    ]}))
    assert summarize_trace(p3) == []
    # sparse producers: non-dict events and non-numeric durs are skipped,
    # not a TypeError mid-triage (fleet_trace merges hit both)
    p4 = tmp_path / "sparse.json"
    p4.write_text(json.dumps({"traceEvents": [
        "not-a-dict",
        {"ph": "X", "name": "bad", "dur": "fast"},
        {"ph": "X", "dur": 3.0},  # nameless -> aggregates under "?"
    ]}))
    rows = summarize_trace(p4)
    assert [r["name"] for r in rows] == ["?"]


def test_export_chrome_trace_is_host_stamped(profile_dir, tmp_path,
                                             monkeypatch):
    """On a shared logdir each host's export carries its host id in the
    filename, so concurrent exports never shadow each other and
    scripts/fleet_trace.py can map files back to hosts."""
    monkeypatch.setenv("DIST_MNIST_TPU_HOST_ID", "3")
    out = export_chrome_trace(profile_dir)
    assert out is not None and out.name.startswith("timeline-h3-")
    monkeypatch.delenv("DIST_MNIST_TPU_HOST_ID")
    # explicit host id beats the (absent) environment
    out = export_chrome_trace(profile_dir, host_id=5)
    assert out.name.startswith("timeline-h5-")
    # no identity at all: the legacy single-process name
    out = export_chrome_trace(profile_dir)
    assert out.name.startswith("timeline-") and "-h" not in out.name


def test_profiler_hook_survives_export_failure(tmp_path, monkeypatch, caplog):
    """export_chrome_trace raising must not take the run down: the hook
    logs and the trace window still closes cleanly."""
    import logging

    from dist_mnist_tpu.hooks.builtin import ProfilerHook
    from dist_mnist_tpu.obs import timeline

    def boom(logdir, out_path=None):
        raise OSError("disk full")

    monkeypatch.setattr(timeline, "export_chrome_trace", boom)

    class FakeLoop:
        initial_step = 0

    hook = ProfilerHook(str(tmp_path), start_step=0, num_steps=1)
    hook.begin(FakeLoop())
    hook.before_step(0)
    x = jnp.ones((32, 32))
    jax.block_until_ready(jax.jit(lambda a: a @ a)(x))
    with caplog.at_level(logging.ERROR, "dist_mnist_tpu.hooks.builtin"):
        hook.after_step(1, None, {"loss": x[0, 0]})  # closes + export fails
    assert not hook._active and hook._done
    assert "chrome trace export failed" in caplog.text
    # the window itself was captured; only the convenience export failed
    assert latest_trace(tmp_path) is not None
    hook.end(None)  # and end() after a completed window is a no-op
